"""Per-phase op census of the *optimized HLO* — the compiled pass budget.

:mod:`.audit` (PR 4) verifies the SPMD communication contract at the
jaxpr level, but the jaxpr is what we *asked for*; on TPU the compiler
owns the hot path, and what actually runs — how many gather passes, how
many sorts, which converts — only exists in the post-optimization HLO
module. In the GSPMD framing (SNIPPETS.md [2]) the compiled program is
the scaling contract, so that is the artifact this module audits.

:func:`census_step_fn` lowers + compiles a jitted step (abstract — the
same harness as :func:`~.memory.compiled_step_report`, nothing executes),
parses the optimized HLO text, and attributes every instruction to its
``obs.scope`` phase: ``jax.named_scope`` components survive XLA
optimization inside ``metadata={op_name="..."}``, including into fused
computations and the ``while``-loops CPU's scatter expander produces. The
result is a :class:`CensusReport` — per phase (full ``detpu/`` scope
path): gather / scatter / sort / cumsum / convert / transpose /
all-to-all passes, convert dtype pairs, fusion count, and estimated bytes
touched. This is the additive per-phase budget of ROADMAP 3(a) (decode,
gather, exchange, bwd expand, dedup, apply), emitted as a dataclass, a
JSON/JSONL record, and a markdown table.

On top of the census sit declarative :class:`PassBudget` contracts
("the ``dedup`` phase holds zero sort/segment-sum passes when the sparse
optimizer declares ``needs_dedup=False``", "at most N gather passes per
lookup group", "no float convert round-trips inside the apply phase"),
enforced by ``tools/hlo_audit.py --strict`` inside ``make verify`` and by
the bench's ``phase_budget`` section (gated by ``tools/compare_bench.py``
— a pass-count regression fails the candidate like a recompile does).

Counting convention: one HLO instruction of a row-op opcode = one pass.
Backend lowering differences are normalized where they matter for the
gates (a CPU ``while`` whose ``op_name`` primitive is a scatter counts as
a scatter pass; a ``reduce-window`` from a ``cumsum`` counts as cumsum),
and budgets are pinned against the same parser on the same backend, so
the gate is self-consistent. Bytes are estimates: the sum of result +
listed-operand element bytes of the counted instruction.

Run under ``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=N`` for an N-position mesh, like
the step auditor; ``tools/hlo_audit.py`` does exactly that.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from ..utils import obs

#: census op kinds a PassBudget can cap (plus "convert_roundtrip" and
#: "fusion"); these are the row-op passes of the ROADMAP 3(a) budget
ROW_OP_KINDS = ("gather", "scatter", "sort", "cumsum", "all_to_all",
                "convert", "transpose")

#: the kinds tools/compare_bench.py gates between bench rounds (convert/
#: transpose counts are reported but not gated: they move with benign
#: layout choices; gather/scatter/sort/cumsum/all-to-all passes are the
#: budget). Keep in sync with compare_bench.PHASE_GATE_KINDS.
GATED_KINDS = ("gather", "scatter", "sort", "cumsum", "all_to_all")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1,
    "f8e4m3fnuz": 1,
}
_FLOAT_DTYPES = frozenset(d for d in _DTYPE_BYTES
                          if d.startswith(("f", "bf")))

# one HLO instruction: `[ROOT ]%name = SHAPE opcode(...)` where SHAPE is a
# tuple `(f32[..], /*index=5*/ s32[..])` (XLA interleaves index comments
# into long tuples) or a plain whitespace-free token — `f32[16,8]{1,0}`,
# or post-layout-assignment TPU spellings like `f32[16,8]{1,0:T(8,128)}`
# / `...S(1)}` (the required whitespace before the opcode disambiguates,
# so `\S+` backtracks off `opcode(` correctly)
_INST_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(?P<shape>\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"(?P<op>[a-z][\w\-]*)\(")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
# the phase-name extractor is SHARED with the scope writer (utils/obs.py
# mints the names) and with the measured-trace parser, so the static and
# measured attributions can never drift onto different spellings
_DETPU_RE = obs.SCOPE_RE
_SHAPE_TOKEN_RE = re.compile(
    r"\b(pred|bf16|f8\w+|[fsuc]\d+)\[([\d,]*)\]")


def _token_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _kind_of(op: str, prim: str) -> Optional[str]:
    """Normalize an HLO opcode (+ the trailing jax primitive from its
    op_name) into a census kind."""
    if op in ("gather", "scatter", "sort", "transpose"):
        return op
    if op == "convert":
        return "convert"
    if op == "all-to-all":
        return "all_to_all"
    if op == "while" and "scatter" in prim:
        return "scatter"  # CPU's scatter expander rewrites scatter->while
    if op == "reduce-window" and "cumsum" in prim:
        return "cumsum"
    if op == "custom-call" and ("all_to_all" in prim or "cumsum" in prim):
        return "all_to_all" if "all_to_all" in prim else "cumsum"
    if op == "fusion":
        return "fusion"
    return None


class CensusError(RuntimeError):
    """Raised by :meth:`CensusReport.raise_on_violations` in strict use."""


@dataclasses.dataclass
class PhasePasses:
    """Aggregated passes of one phase (one full ``detpu/`` scope path)."""
    path: str                       # e.g. "sparse_apply/sparse_apply_w8/dedup"
    leaf: str                       # last component, e.g. "dedup"
    counts: Dict[str, int]          # kind -> pass count (ROW_OP_KINDS)
    convert_pairs: Dict[str, int]   # "bf16->f32" -> count
    fusions: int
    instructions: int               # every instruction attributed here
    bytes_est: int                  # result+operand bytes of counted passes

    def roundtrips(self) -> int:
        """Float narrowing/widening convert pairs inside this phase:
        ``min(count[a->b], count[b->a])`` summed over unordered FLOAT dtype
        pairs. A value squeezed f32->bf16->f32 inside one phase silently
        lost 16 bits of mantissa; integer casts are excluded (index
        arithmetic legitimately round-trips)."""
        n = 0
        seen = set()
        for pair, cnt in self.convert_pairs.items():
            a, b = pair.split("->")
            if a not in _FLOAT_DTYPES or b not in _FLOAT_DTYPES or a == b:
                continue
            key = tuple(sorted((a, b)))
            if key in seen:
                continue
            seen.add(key)
            n += min(cnt, self.convert_pairs.get(f"{b}->{a}", 0))
        return n

    def to_json(self) -> Dict[str, Any]:
        d = dict(self.counts)
        d.update(path=self.path, leaf=self.leaf, fusion=self.fusions,
                 instructions=self.instructions, bytes_est=self.bytes_est,
                 convert_pairs=dict(self.convert_pairs),
                 convert_roundtrip=self.roundtrips())
        return d


@dataclasses.dataclass(frozen=True)
class PassBudget:
    """One declarative cap on the passes of a phase.

    ``phase`` is an ``fnmatch`` glob tested against each phase's full
    ``detpu`` path AND its leaf name (so ``"dedup"`` hits the dedup scope
    wherever it nests, and ``"*/lookup_*/packed_gather"`` pins the gathers
    of every lookup group). ``kind`` is a :data:`ROW_OP_KINDS` entry,
    ``"fusion"``, or ``"convert_roundtrip"``. ``per_path=True`` applies
    the cap to every matching phase individually (per-group budgets);
    otherwise the counts of all matching phases sum first.
    ``max_passes=None`` means unbounded, so a floor-only contract
    (``min_passes=N`` alone) guards a pass whose *disappearance* would be
    the bug without also capping it."""
    phase: str
    kind: str
    max_passes: Optional[int] = None
    min_passes: int = 0
    per_path: bool = False
    reason: str = ""

    def __post_init__(self) -> None:
        if self.max_passes is not None and self.min_passes > self.max_passes:
            raise ValueError(
                f"PassBudget({self.phase!r}, {self.kind!r}): min_passes="
                f"{self.min_passes} > max_passes={self.max_passes} can "
                "never hold")


def dedup_zero_contracts(reason: str) -> List[PassBudget]:
    """The SGD pass-cut contract: a ``detpu/dedup`` scope must compile to
    NOTHING — no sort, no segment-sum scatter, no cumsum boundary pass, no
    gather — when the optimizer declares ``needs_dedup=False``."""
    return [PassBudget("dedup", k, max_passes=0, reason=reason)
            for k in ("sort", "scatter", "cumsum", "gather")]


def default_contracts(emb_optimizer=None) -> List[PassBudget]:
    """Config-independent contracts for a hybrid train step census.

    Today that is the dedup budget: when the sparse optimizer declares
    ``needs_dedup=False`` (and ``DETPU_SGD_DEDUP`` does not force the pass
    back in), the compiled dedup phase must be empty. Shape-dependent
    budgets (gathers per lookup group, pinned dedup counts for stateful
    optimizers) belong to the caller — ``tools/hlo_audit.py`` pins them
    for the reference configurations."""
    from ..parallel.optimizers import sgd_dedup_forced

    out: List[PassBudget] = []
    if emb_optimizer is not None and not getattr(
            emb_optimizer, "needs_dedup", True) and not sgd_dedup_forced():
        out += dedup_zero_contracts(
            f"{type(emb_optimizer).__name__} declares needs_dedup=False "
            "(linear update: duplicates are scatter-add-safe)")
    return out


@dataclasses.dataclass
class CensusReport:
    """Structured result of one optimized-HLO census."""
    label: str
    world: int
    backend: Optional[str]
    phases: Dict[str, PhasePasses]        # keyed by full detpu path
    total_instructions: int
    unattributed_row_ops: int             # counted kinds with no detpu scope
    violations: List[str]

    @property
    def ok(self) -> bool:
        return not self.violations

    def _matching(self, glob: str) -> List[PhasePasses]:
        return [p for p in self.phases.values()
                if fnmatch.fnmatchcase(p.path, glob)
                or fnmatch.fnmatchcase(p.leaf, glob)]

    def _phase_count(self, p: PhasePasses, kind: str) -> int:
        if kind == "convert_roundtrip":
            return p.roundtrips()
        if kind == "fusion":
            return p.fusions
        return p.counts.get(kind, 0)

    def passes(self, phase_glob: str, kind: str) -> int:
        """Total passes of ``kind`` across every phase matching the glob."""
        return sum(self._phase_count(p, kind)
                   for p in self._matching(phase_glob))

    def check(self, contracts: Sequence[PassBudget]) -> "CensusReport":
        """Evaluate pass budgets; violations append to ``self.violations``
        (idempotent per distinct message). Returns self for chaining."""
        for b in contracts:
            matched = self._matching(b.phase)
            units: List[Tuple[str, int]]
            if b.per_path:
                units = [(p.path, self._phase_count(p, b.kind))
                         for p in matched]
                if not matched and b.min_passes > 0:
                    # a min contract must fire when the phase itself is
                    # gone, not just when it compiled to too few passes
                    units = [(b.phase, 0)]
            else:
                # no matches sums to 0, which also makes a min contract
                # fire on a vanished phase
                units = [(b.phase, sum(self._phase_count(p, b.kind)
                                       for p in matched))]
            for where, n in units:
                msg = None
                if b.max_passes is not None and n > b.max_passes:
                    msg = (f"pass budget exceeded: {n} {b.kind} pass(es) in "
                           f"phase '{where}' (budget {b.max_passes})")
                elif n < b.min_passes:
                    msg = (f"pass budget underrun: {n} {b.kind} pass(es) in "
                           f"phase '{where}' (expected >= {b.min_passes} — "
                           "a pass the contract relies on disappeared)")
                if msg:
                    if b.reason:
                        msg += f" — {b.reason}"
                    if msg not in self.violations:
                        self.violations.append(msg)
        return self

    def raise_on_violations(self) -> "CensusReport":
        if self.violations:
            raise CensusError(
                "HLO pass census failed:\n  - "
                + "\n  - ".join(self.violations))
        return self

    def phase_table(self) -> Dict[str, Dict[str, int]]:
        """The compact per-phase budget the bench record embeds: kind
        counts + fusion + bytes_est per phase path, gated kinds first."""
        out: Dict[str, Dict[str, int]] = {}
        for path, p in sorted(self.phases.items()):
            row = {k: p.counts.get(k, 0) for k in ROW_OP_KINDS}
            row["fusion"] = p.fusions
            row["convert_roundtrip"] = p.roundtrips()
            row["bytes_est"] = p.bytes_est
            out[path or "(unscoped)"] = row
        return out

    def markdown(self) -> str:
        """The per-phase budget as a markdown table (docs / PR bodies)."""
        cols = list(ROW_OP_KINDS) + ["fusion", "bytes_est"]
        lines = ["| phase | " + " | ".join(cols) + " |",
                 "|---" * (len(cols) + 1) + "|"]
        for path, row in self.phase_table().items():
            cells = [str(row[c]) for c in cols]
            lines.append(f"| `{path}` | " + " | ".join(cells) + " |")
        return "\n".join(lines)

    def to_json(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "world": self.world,
            "backend": self.backend,
            "ok": self.ok,
            "phases": {k or "(unscoped)": p.to_json()
                       for k, p in sorted(self.phases.items())},
            "total_instructions": self.total_instructions,
            "unattributed_row_ops": self.unattributed_row_ops,
            "violations": list(self.violations),
        }

    def dumps(self, **kw: Any) -> str:
        return json.dumps(self.to_json(), **kw)


# ------------------------------------------------------------- the parser


def census_of_text(txt: str, *, label: str = "step", world: int = 1,
                   backend: Optional[str] = None) -> CensusReport:
    """Parse optimized HLO module text into a :class:`CensusReport`.

    Pure text -> dataclass (no jax beyond what the caller already did):
    every instruction line — entry computation, fused computations, while
    bodies, sort comparators — is attributed to the ``detpu/`` scope path
    recorded in its ``metadata.op_name``."""
    phases: Dict[str, PhasePasses] = {}
    total = 0
    unattributed = 0
    for line in txt.splitlines():
        m = _INST_RE.match(line)
        if m is None:
            continue
        total += 1
        op = m.group("op")
        nm = _OPNAME_RE.search(line)
        op_name = nm.group(1) if nm else ""
        parts = _DETPU_RE.findall(op_name)
        path = "/".join(parts)
        prim = op_name.rsplit("/", 1)[-1] if op_name else ""
        kind = _kind_of(op, prim)
        ph = phases.get(path)
        if ph is None:
            ph = phases[path] = PhasePasses(
                path=path, leaf=parts[-1] if parts else "",
                counts={}, convert_pairs={}, fusions=0, instructions=0,
                bytes_est=0)
        ph.instructions += 1
        if kind is None:
            continue
        if kind == "fusion":
            ph.fusions += 1
            continue
        if not parts:
            unattributed += 1
        ph.counts[kind] = ph.counts.get(kind, 0) + 1
        tokens = _SHAPE_TOKEN_RE.findall(line)
        ph.bytes_est += sum(_token_bytes(dt, dims) for dt, dims in tokens)
        if kind == "convert" and len(tokens) >= 2:
            # first token is the result shape, second the operand
            pair = f"{tokens[1][0]}->{tokens[0][0]}"
            ph.convert_pairs[pair] = ph.convert_pairs.get(pair, 0) + 1
    return CensusReport(
        label=label, world=world, backend=backend, phases=phases,
        total_instructions=total, unattributed_row_ops=unattributed,
        violations=[])


# -------------------------------------------------------- the entry points


def census_step_fn(step_fn, args: Sequence[Any], *,
                   world: int = 1,
                   label: str = "step",
                   contracts: Optional[Sequence[PassBudget]] = None
                   ) -> CensusReport:
    """Compile a jitted step abstractly and census its optimized HLO.

    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct`` pytrees —
    ``step_fn.lower(*args).compile()`` never executes anything (the
    :func:`~.memory.compiled_step_report` harness). Plain callables are
    wrapped in ``jax.jit`` first.
    """
    if not hasattr(step_fn, "lower"):
        step_fn = jax.jit(step_fn)
    txt = step_fn.lower(*args).compile().as_text()
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - stamp is best-effort
        backend = None
    rep = census_of_text(txt, label=label, world=world, backend=backend)
    if rep.total_instructions == 0:
        # a compiled step always holds instructions: zero means THIS
        # backend's HLO text didn't match the parser, and every budget
        # downstream would pass vacuously — fail loudly instead
        raise CensusError(
            f"census of {label!r} parsed 0 instructions from a "
            f"{len(txt)}-byte compiled module (backend {backend}) — "
            "unrecognized HLO text format; the pass-budget gate cannot "
            "run on it")
    if contracts:
        rep.check(contracts)
    return rep


def census_train_step(de,
                      loss_fn,
                      dense_tx,
                      emb_optimizer,
                      cat_inputs,
                      batch,
                      mesh=None,
                      lr_schedule=1.0,
                      with_metrics: Optional[bool] = None,
                      nan_guard: Optional[bool] = None,
                      telemetry=None,
                      dense_params=None,
                      state=None,
                      contracts: Optional[Sequence[PassBudget]] = None,
                      label: str = "hybrid_train_step") -> CensusReport:
    """Build the hybrid train step exactly like
    :func:`~..parallel.trainer.make_hybrid_train_step` (the
    :func:`~.audit.audit_train_step` build, shared conventions: abstract
    state derived via ``eval_shape`` from ``dense_params`` when ``state``
    is omitted, metrics/guard/telemetry variants included) and census its
    optimized HLO against ``contracts``.

    ``contracts=None`` applies :func:`default_contracts` for the given
    ``emb_optimizer`` (today: the empty-dedup budget when it declares
    ``needs_dedup=False``); pass an explicit list — possibly empty — to
    override.
    """
    from .audit import build_abstract_step

    step, args, _, _, _, _ = build_abstract_step(
        de, loss_fn, dense_tx, emb_optimizer, cat_inputs, batch,
        mesh=mesh, lr_schedule=lr_schedule, with_metrics=with_metrics,
        nan_guard=nan_guard, telemetry=telemetry,
        dense_params=dense_params, state=state)

    if contracts is None:
        contracts = default_contracts(emb_optimizer)
    return census_step_fn(step, args, world=de.world_size, label=label,
                          contracts=contracts)

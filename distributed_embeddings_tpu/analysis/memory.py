"""Static capacity accounting: per-table/slab HBM budgets and
compiled-step memory/FLOP reports.

The paper's sharding exists because embedding tables dominate HBM — yet
nothing in the repo could answer "how many bytes does table 17 actually
cost on its rank, optimizer state included, layout padding included?"
or "what does the compiled step peak at?" without running on a chip and
eyeballing allocator logs. This module answers both *abstractly*:

* :func:`table_memory_report` prices every global table and every width
  slab from the strategy alone — parameter bytes, optimizer-state bytes
  (``jax.eval_shape`` over the sparse optimizer's ``init``, so any
  optimizer prices itself), lane/row padding overhead, per-rank live
  bytes. Pure metadata; no arrays are materialized. Since PR 8 this is
  also the *calibration target* of :mod:`.plan_audit`'s jax-free byte
  model (``tools/plan_audit.py --strict`` requires the two to agree),
  rather than the only source of capacity numbers.
* :func:`compiled_step_report` lowers + compiles a jitted step (CPU-safe
  — compilation never executes anything) and reads XLA's own
  ``memory_analysis()`` / ``cost_analysis()``: argument/output/temp/
  alias bytes and FLOPs. Probe-guarded like :func:`~.audit.
  audit_train_step`: backends that expose no analysis yield a report
  with an ``error`` field, never an exception.
* :func:`step_memory_report` fuses the two around a hybrid train step
  built exactly like :func:`~..parallel.trainer.make_hybrid_train_step`
  builds it, plus rough per-table per-step HBM/FLOP estimates derived
  from the input encodings (gather + scatter-update traffic).

Run under ``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=N`` for an N-position mesh —
the same harness as the step auditor; ``tools/obs_report.py`` does it
for the reference configs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import trainer as trainer_mod


def _itemsize(dtype) -> int:
    return jnp.dtype(dtype).itemsize


def _leaf_bytes(tree) -> int:
    """Total bytes of a ShapeDtypeStruct/array pytree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * _itemsize(dtype)
    return total


def table_memory_report(de, emb_optimizer=None,
                        param_dtype=jnp.float32) -> Dict[str, Any]:
    """Price ``de``'s layout without materializing anything.

    Returns ``{"tables": [...], "slabs": {...}, "per_rank": [...],
    "totals": {...}}``:

    * ``tables[tid]`` — rows, width, logical parameter bytes, slice
      count, whether row-sliced, owning ranks;
    * ``slabs[wN]`` — the physical ``[world, phys_cap, phys_w]`` stacked
      slab: allocated vs live bytes (the difference is lane packing +
      row alignment + rank-imbalance padding), optimizer-state bytes
      for the slab (from ``eval_shape(emb_optimizer.init)``);
    * ``per_rank[r]`` — live parameter bytes and table count actually
      placed on rank ``r`` (the placement-imbalance view);
    * ``totals`` — params allocated/live, optimizer state, padding
      fraction.
    """
    isz = _itemsize(param_dtype)
    world = de.world_size

    tables: List[Dict[str, Any]] = []
    for tid, cfg in enumerate(de.strategy.global_configs):
        rows, width = int(cfg["input_dim"]), int(cfg["output_dim"])
        ranks = [r for r, ids in enumerate(de.strategy.table_ids_list)
                 if tid in ids]
        tables.append({
            "table_id": tid,
            "rows": rows,
            "width": width,
            "param_bytes": rows * width * isz,
            "slices": int(de._slices_per_table[tid]),
            "row_sliced": tid in de.strategy.row_sliced_tables,
            "ranks": ranks,
        })

    # abstract global params — exactly what de.init would build
    abs_params = {
        f"w{w}": jax.ShapeDtypeStruct(
            (world, de.phys_cap[w], de.phys_w[w]), param_dtype)
        for w in de.widths}
    opt_bytes_by_width: Dict[str, int] = {}
    opt_error = None
    if emb_optimizer is not None:
        try:
            abs_state = jax.eval_shape(emb_optimizer.init, abs_params)
            if isinstance(abs_state, dict):
                for k in abs_params:
                    opt_bytes_by_width[k] = _leaf_bytes(abs_state.get(k))
            else:  # non-dict state: price it once under the first width
                opt_bytes_by_width[next(iter(abs_params))] = \
                    _leaf_bytes(abs_state)
        except Exception as e:  # noqa: BLE001 - accounting must not throw
            opt_error = f"{type(e).__name__}: {e}"

    slabs: Dict[str, Any] = {}
    live_by_rank = [0] * world
    tables_by_rank = [0] * world
    for r, cfgs in enumerate(de.strategy.local_configs_list):
        tables_by_rank[r] = len(cfgs)
        for cfg in cfgs:
            live_by_rank[r] += (int(cfg["input_dim"])
                                * int(cfg["output_dim"]) * isz)
    for w in de.widths:
        key = f"w{w}"
        shape = (world, de.phys_cap[w], de.phys_w[w])
        alloc = int(np.prod(shape, dtype=np.int64)) * isz
        live = sum(int(cfg["input_dim"]) * w * isz
                   for cfgs in de.strategy.local_configs_list
                   for cfg in cfgs if int(cfg["output_dim"]) == w)
        slabs[key] = {
            "shape": list(shape),
            "param_bytes": alloc,
            "live_bytes": live,
            "padding_bytes": alloc - live,
            "opt_state_bytes": opt_bytes_by_width.get(key),
        }

    alloc_total = sum(s["param_bytes"] for s in slabs.values())
    live_total = sum(s["live_bytes"] for s in slabs.values())
    opt_total = (sum(v for v in opt_bytes_by_width.values())
                 if opt_bytes_by_width else None)
    return {
        "world": world,
        "param_dtype": str(jnp.dtype(param_dtype)),
        "tables": tables,
        "slabs": slabs,
        "per_rank": [{"rank": r, "live_param_bytes": live_by_rank[r],
                      "tables": tables_by_rank[r]}
                     for r in range(world)],
        "totals": {
            "param_bytes_allocated": alloc_total,
            "param_bytes_live": live_total,
            "padding_frac": ((alloc_total - live_total) / alloc_total
                             if alloc_total else 0.0),
            "opt_state_bytes": opt_total,
            "opt_state_error": opt_error,
            # the slab layout is rank-uniform ([world, cap, w] stacked
            # tables), so per-rank allocated/optimizer shares are exact
            # divisions — the figures analysis.plan_audit predicts
            # jax-free and calibrates against these
            "param_bytes_allocated_per_rank": alloc_total // world,
            "opt_state_bytes_per_rank": (None if opt_total is None
                                         else opt_total // world),
        },
    }


def compiled_step_report(step_fn, args) -> Dict[str, Any]:
    """XLA's own memory/cost view of a jitted callable, by abstract
    lowering + compilation (nothing executes; safe on CPU and on any
    backend whose compiler is reachable).

    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct`` pytrees.
    Missing analyses (backend-dependent) leave their fields ``None``
    with the reason in ``error`` — a report, never an exception.
    """
    out: Dict[str, Any] = {
        "argument_bytes": None, "output_bytes": None, "temp_bytes": None,
        "alias_bytes": None, "generated_code_bytes": None,
        "peak_bytes_est": None, "flops": None, "bytes_accessed": None,
        "backend": None, "error": None,
    }
    if not hasattr(step_fn, "lower"):
        out["error"] = "step_fn has no .lower() — pass the jit wrapper"
        return out
    try:
        compiled = step_fn.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 - probe-guarded by contract
        out["error"] = f"lower/compile failed: {type(e).__name__}: {e}"
        return out
    try:
        out["backend"] = jax.default_backend()
    except Exception:  # noqa: BLE001 - stamp is best-effort
        pass
    try:
        mem = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 - analysis is backend-optional
        mem, out["error"] = None, f"memory_analysis: {e}"
    if mem is not None:
        arg = int(getattr(mem, "argument_size_in_bytes", 0))
        outb = int(getattr(mem, "output_size_in_bytes", 0))
        tmp = int(getattr(mem, "temp_size_in_bytes", 0))
        ali = int(getattr(mem, "alias_size_in_bytes", 0))
        out.update(
            argument_bytes=arg, output_bytes=outb, temp_bytes=tmp,
            alias_bytes=ali,
            generated_code_bytes=int(
                getattr(mem, "generated_code_size_in_bytes", 0)),
            # donated (aliased) buffers are counted once: they are the
            # same HBM on the way in and out
            peak_bytes_est=arg + outb + tmp - ali)
    try:
        cost = compiled.cost_analysis()
    except Exception as e:  # noqa: BLE001 - analysis is backend-optional
        cost = None
        out["error"] = (out["error"] or "") + f" cost_analysis: {e}"
    if cost:
        # some jax versions return [dict], others dict
        c = cost[0] if isinstance(cost, (list, tuple)) else cost
        if isinstance(c, dict):
            if c.get("flops") is not None:
                out["flops"] = float(c["flops"])
            if c.get("bytes accessed") is not None:
                out["bytes_accessed"] = float(c["bytes accessed"])
    return out


def _input_traffic_estimates(de, cat_inputs,
                             param_dtype) -> List[Dict[str, Any]]:
    """Rough per-table per-step HBM/FLOP estimates from the input
    shapes: each live id costs one row gather forward plus a
    read-modify-write scatter update backward (~3 row passes), and
    ~4 flops per gathered element (combine + backward accumulate).
    Upper bounds for ragged inputs (priced at static capacity)."""
    from ..ops.embedding_lookup import Ragged

    isz = _itemsize(param_dtype)
    est: Dict[int, Dict[str, float]] = {}
    for i, inp in enumerate(cat_inputs):
        tid = de.strategy.input_table_map[i]
        if isinstance(inp, Ragged):
            ids = int(np.shape(inp.values)[0])  # static capacity
        else:
            shape = tuple(getattr(inp, "shape", ()))
            ids = int(np.prod(shape, dtype=np.int64)) if shape else 1
        e = est.setdefault(tid, {"ids_per_step": 0.0})
        e["ids_per_step"] += ids
    out = []
    for tid in sorted(est):
        ids = est[tid]["ids_per_step"]
        width = int(de.strategy.global_configs[tid]["output_dim"])
        out.append({
            "table_id": tid,
            "ids_per_step": int(ids),
            "est_hbm_bytes_per_step": int(3 * ids * width * isz),
            "est_flops_per_step": int(4 * ids * width),
        })
    return out


def step_memory_report(de, loss_fn, dense_tx, emb_optimizer,
                       cat_inputs, batch, mesh=None, lr_schedule=1.0,
                       with_metrics: bool = False,
                       nan_guard: Optional[bool] = None,
                       telemetry=None,
                       dense_params=None, state=None,
                       param_dtype=jnp.float32) -> Dict[str, Any]:
    """The full static capacity report for one hybrid train step:
    :func:`table_memory_report` + :func:`compiled_step_report` of the
    step built exactly like ``make_hybrid_train_step`` builds it
    (metrics/guard/telemetry variants included) + per-table traffic
    estimates. Inputs follow :func:`~.audit.audit_train_step`'s
    contract — ``ShapeDtypeStruct`` pytrees are fine, nothing executes.
    """
    from ..utils import obs
    from . import telemetry as tel

    if nan_guard is None:
        nan_guard = obs.nanguard_enabled()
    tel_cfg = tel.resolve_config(telemetry)

    if state is None:
        if dense_params is None:
            raise ValueError(
                "step_memory_report needs dense_params (to derive an "
                "abstract state) or an explicit state=")
        state = jax.eval_shape(
            lambda k, dp: trainer_mod.init_hybrid_state(
                de, emb_optimizer, dp, dense_tx, k, dtype=param_dtype),
            jax.random.key(0), dense_params)

    step = trainer_mod.make_hybrid_train_step(
        de, loss_fn, dense_tx, emb_optimizer, mesh=mesh,
        lr_schedule=lr_schedule, with_metrics=with_metrics,
        nan_guard=nan_guard, telemetry=tel_cfg if tel_cfg else False)
    args = [state, cat_inputs, batch]
    if tel_cfg is not None:
        args.append(jax.eval_shape(
            lambda: tel.init_telemetry(de, tel_cfg)))

    return {
        "layout": table_memory_report(de, emb_optimizer,
                                      param_dtype=param_dtype),
        "compiled": compiled_step_report(step, tuple(args)),
        "per_table_traffic": _input_traffic_estimates(
            de, cat_inputs, param_dtype),
        "variant": {
            "with_metrics": bool(with_metrics),
            "nan_guard": bool(nan_guard),
            "telemetry": tel_cfg._asdict() if tel_cfg else None,
            "world": de.world_size,
        },
    }

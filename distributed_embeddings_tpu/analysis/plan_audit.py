"""Plan-time capacity & cost auditor — static HBM/comms contracts.

The repo has two static gates already: :mod:`.audit` (PR 4) checks the
jaxpr we ASK the compiler for and :mod:`.hlo_census` (PR 7) checks what
XLA EMITS. Both need a traceable step, i.e. a built
:class:`~..parallel.dist_embedding.DistributedEmbedding` and a jax
import. This module is the gate that runs *before either*: a pure-host
analytic model of what a :class:`~..parallel.strategy.
DistEmbeddingStrategy` plan will cost once executed — per-rank
parameter + optimizer + exchange-buffer bytes, per-step all-to-all
payload bytes, padded-group shape count (the recompile surface), apply-
scatter slab sizes against the measured cliff, placement imbalance —
with nothing but integer arithmetic over the plan. GSPMD-style systems
validate placements before touching a pod (SNIPPETS.md [2]'s "8-chip →
6000-chip without changing application code"); this is that validation
for the 26-table / 188M-row Criteo-1TB shapes the ≥2M samples/s
north star is projected at.

The model is *calibrated*, not parallel-universe arithmetic:

* slab geometry (lane packing, row alignment, per-width physical
  capacity) mirrors ``DistributedEmbedding.__init__`` /
  ``ops/packed_slab.py`` exactly and is pinned to them by test;
* exchange layout (``l_max``/``s_max``/groups) comes from the
  executor's OWN plan builder (:func:`~..parallel.plan.build_plan`,
  numpy-only — no jax executes);
* per-step payload bytes use the same ``(world-1) * padded_block``
  formula ``DistributedEmbedding.step_metrics`` reports on device, so
  the prediction is checkable against the measured ``*_a2a_bytes``
  step metrics;
* parameter/optimizer byte totals are cross-checked against
  :func:`.memory.table_memory_report`'s ``eval_shape`` accounting
  (which becomes the calibration target rather than the only source)
  by :func:`compare_with_memory` — ``tools/plan_audit.py --strict``
  enforces agreement.

On top sit declarative :class:`PlanContract` s (max per-rank HBM, max
a2a bytes/step, zero slabs past the scatter cliff, every rank owns a
table, padded-group ceiling), enforced by ``tools/plan_audit.py
--strict`` inside ``make verify`` — including a ``criteo1tb`` case with
the real vocab vector — and consumed by planners through
:meth:`DistEmbeddingStrategy.predicted_cost` / :func:`rank_strategies`
to rank candidate plans by predicted cost before anything is built.

This module is also the repo's **capacity registry**: chip capability
numbers (HBM bytes, ICI bandwidth, peak FLOPs) and measured byte
thresholds (the 2.7→8.65 GB scatter cliff) live HERE as named
constants. The detlint rule ``hardcoded-capacity`` forbids capacity
literals elsewhere in the package — a device count or HBM size inlined
at a call site drifts silently when hardware assumptions change.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# --------------------------------------------------------------------------
# capacity registry (the single home for hardware capability numbers;
# everything else in the package must reference these — detlint rule
# `hardcoded-capacity`)
# --------------------------------------------------------------------------

#: TPU vector lane count — the packed-slab layout constant
#: (mirrors ``ops/packed_slab.LANES``; agreement is test-pinned so the
#: jax-free arithmetic here cannot drift from the executor's).
LANES = 128


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Capability numbers of one accelerator generation.

    ``hbm_headroom`` is the fraction of HBM a plan may budget: XLA
    reserves workspace, the step needs transients (exchange buffers are
    priced separately but fusions/temps are not), and a plan sized to
    100% of HBM OOMs on the first compile with different flags.
    """

    name: str
    hbm_bytes: int
    hbm_gbps: float
    ici_eff_gbps: float
    bf16_peak_flops: float
    hbm_headroom: float = 0.90


#: Known chips. v5e (v5 lite): 16 GiB HBM at 819 GB/s, 197 TFLOP/s bf16
#: peak, ~100 GB/s effective per-chip all-to-all bandwidth over ICI
#: (2D torus, 4x 400 Gbps links; conservative effective figure — the
#: same numbers bench.py's v5e-16 budget uses).
CHIP_SPECS: Dict[str, ChipSpec] = {
    "v5e": ChipSpec("v5e", hbm_bytes=16 * 1024**3, hbm_gbps=819.0,
                    ici_eff_gbps=100.0, bf16_peak_flops=197e12),
}

#: The measured apply-scatter rate cliff (docs/perf_tpu.md, VERDICT.md
#: Weak #3): a single uncapped scatter into a 2.7 GB slab ran at 43 ms
#: while the same op into an 8.65 GB slab took 70 ms — the cliff lies
#: inside that bracket. Slabs at or past the upper bound are flagged as
#: contract violations; slabs inside the bracket are reported as
#: "cliff_band" (exposed, but not proven slow).
SCATTER_CLIFF_SAFE_BYTES = 2_700_000_000
SCATTER_CLIFF_BYTES = 8_650_000_000

#: Default ceiling on padded (width, kind, hotness) group shapes per
#: plan. Each group is one statically-shaped exchange region — the
#: compiled program is O(#groups) heavy ops, and every distinct
#: (encodings, batch) signature compiles once; the zoo-scale invariant
#: tests pin <= 12 groups at 2002 tables, so a plan past this ceiling
#: has lost the rank-uniform layout property.
DEFAULT_MAX_GROUPS = 16


# --------------------------------------------------------------------------
# jax-free mirrors of the packed-slab arithmetic (ops/packed_slab.py);
# the parity test in tests/test_plan_audit.py pins these to the real ones
# --------------------------------------------------------------------------


def _pack_factor(width: int) -> int:
    return max(1, LANES // int(width))


def _phys_width(width: int) -> int:
    return LANES if _pack_factor(width) > 1 else int(width)


def _align_rows(rows: int, width: int) -> int:
    p = _pack_factor(width)
    return -(-int(rows) // p) * p


_DTYPE_BYTES = {
    "bool": 1, "int8": 1, "uint8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "float32": 4, "int32": 4, "uint32": 4,
    "float64": 8, "int64": 8, "uint64": 8,
}


def _dtype_name(dtype) -> str:
    name = getattr(dtype, "__name__", None)
    if name:
        return name
    try:
        return str(np.dtype(dtype))
    except TypeError:
        return str(dtype)


def _dtype_bytes(dtype) -> int:
    """Itemsize of a dtype-like without importing jax (``np.dtype`` knows
    bfloat16 only when ml_dtypes is registered, so the extension names
    are table-driven)."""
    name = _dtype_name(dtype)
    if name in _DTYPE_BYTES:
        return _DTYPE_BYTES[name]
    return int(np.dtype(dtype).itemsize)


# --------------------------------------------------------------------------
# optimizer state model (calibrated against eval_shape over the real
# optimizers' init by compare_with_memory)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class OptimizerModel:
    """Byte model of one sparse slab optimizer: ``slots`` whole-slab
    state copies in the slab dtype (SGD 0, Adagrad/Momentum 1, Adam 2)
    plus ``aux_bytes_per_slab`` per-rank bookkeeping (Adam's ``[.., 1,
    1]`` f32 step count)."""

    name: str
    slots: int
    aux_bytes_per_slab: int = 0


OPTIMIZER_MODELS: Dict[str, OptimizerModel] = {
    "sgd": OptimizerModel("sgd", 0),
    "adagrad": OptimizerModel("adagrad", 1),
    "momentum": OptimizerModel("momentum", 1),
    "adam": OptimizerModel("adam", 2, aux_bytes_per_slab=4),
}


def optimizer_model(optimizer) -> OptimizerModel:
    """Resolve an optimizer argument — a registry name, an
    :class:`OptimizerModel`, or a ``Sparse*`` instance/class (matched by
    class name) — to its byte model."""
    if isinstance(optimizer, OptimizerModel):
        return optimizer
    if isinstance(optimizer, str):
        try:
            return OPTIMIZER_MODELS[optimizer.lower()]
        except KeyError:
            raise ValueError(
                f"unknown optimizer {optimizer!r} (have: "
                f"{', '.join(sorted(OPTIMIZER_MODELS))})") from None
    name = type(optimizer).__name__ if not isinstance(optimizer, type) \
        else optimizer.__name__
    key = name.lower().removeprefix("sparse")
    if key in OPTIMIZER_MODELS:
        return OPTIMIZER_MODELS[key]
    raise ValueError(
        f"cannot derive a byte model from optimizer {name!r}; pass an "
        "OptimizerModel or a registry name "
        f"({', '.join(sorted(OPTIMIZER_MODELS))})")


# --------------------------------------------------------------------------
# slab geometry from the strategy alone (mirror of
# DistributedEmbedding.__init__'s width grouping; test-pinned)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SlabGeometry:
    """Physical slab layout a strategy implies: per width the packed
    ``[world, phys_cap, phys_w]`` stacked-table shape every rank
    allocates, plus each local table's logical row offset."""

    widths: Tuple[int, ...]
    row_offsets_list: Tuple[Tuple[int, ...], ...]
    rows_cap: Dict[int, int]
    phys_cap: Dict[int, int]
    phys_w: Dict[int, int]

    def rank_param_bytes(self, param_bytes: int) -> int:
        """Allocated slab bytes per rank (identical on every rank: the
        layout is SPMD-uniform, padding rows absorb imbalance)."""
        return sum(self.phys_cap[w] * self.phys_w[w] * param_bytes
                   for w in self.widths)


def slab_geometry(strategy) -> SlabGeometry:
    """Derive the packed slab geometry from a planned strategy — the
    same width grouping / row alignment / max-over-ranks capacity
    computation ``DistributedEmbedding.__init__`` performs, without
    building the layer (or importing jax)."""
    widths = sorted({int(c["output_dim"])
                     for cfgs in strategy.local_configs_list
                     for c in cfgs})
    row_offsets_list: List[Tuple[int, ...]] = []
    per_rank_rows: List[Dict[int, int]] = []
    for cfgs in strategy.local_configs_list:
        used = {w: 0 for w in widths}
        offsets = []
        for c in cfgs:
            w = int(c["output_dim"])
            offsets.append(used[w])
            used[w] += _align_rows(int(c["input_dim"]), w)
        row_offsets_list.append(tuple(offsets))
        per_rank_rows.append(used)
    rows_cap = {w: max(max(max(r[w] for r in per_rank_rows), 1),
                       _pack_factor(w)) for w in widths}
    rows_cap = {w: _align_rows(rows_cap[w], w) for w in widths}
    phys_cap = {w: rows_cap[w] // _pack_factor(w) for w in widths}
    phys_w = {w: _phys_width(w) for w in widths}
    return SlabGeometry(widths=tuple(widths),
                        row_offsets_list=tuple(row_offsets_list),
                        rows_cap=rows_cap, phys_cap=phys_cap, phys_w=phys_w)


def encodings_from_inputs(strategy, cat_inputs, world: int
                          ) -> Tuple[List[tuple], int]:
    """Derive the exchange-plan encodings and the per-shard batch from
    abstract (or concrete) GLOBAL inputs — the shapes a caller hands the
    distributed step. Dense arrays map like
    ``DistributedEmbedding._dense_enc`` (leading dim = global batch);
    Ragged-likes (anything with ``values``/``row_splits``) carry their
    per-shard static capacity as ``values.shape[0] // world``.
    """
    encs: List[tuple] = []
    b_local: Optional[int] = None

    def see_batch(gb: int, what: str) -> None:
        nonlocal b_local
        if gb % world:
            raise ValueError(
                f"{what}: global batch {gb} not divisible by world {world}")
        lb = gb // world
        if b_local is None:
            b_local = lb
        elif b_local != lb:
            raise ValueError(
                f"{what}: per-shard batch {lb} disagrees with {b_local}")

    for i, inp in enumerate(cat_inputs):
        tid = strategy.input_table_map[i]
        comb = strategy.global_configs[tid].get("combiner")
        if hasattr(inp, "row_splits"):
            cap = int(inp.values.shape[0])
            nsplit = int(inp.row_splits.shape[0])
            if cap % world or nsplit % world:
                raise ValueError(
                    f"input {i}: ragged shapes {(cap, nsplit)} not "
                    f"divisible by world {world}")
            see_batch(nsplit - world, f"input {i}")
            kind = "rw" if getattr(inp, "weights", None) is not None else "r"
            encs.append((kind, cap // world))
            continue
        shape = tuple(int(d) for d in inp.shape)
        if not shape:
            raise ValueError(f"input {i}: scalar inputs are not routable")
        see_batch(shape[0], f"input {i}")
        dims = shape[1:]
        if comb:
            h = dims[-1] if dims else 1
            ns = int(np.prod(dims[:-1], dtype=np.int64)) if len(dims) > 1 \
                else 1
            encs.append(("d", h, ns))
        else:
            ns = int(np.prod(dims, dtype=np.int64)) if dims else 1
            encs.append(("d", 1, ns))
    if b_local is None:
        raise ValueError("no inputs to derive a batch from")
    return encs, b_local


# --------------------------------------------------------------------------
# the report
# --------------------------------------------------------------------------


def _gb(x: float) -> float:
    return x / 1024**3


@dataclasses.dataclass
class RankBudget:
    """Predicted steady-state bytes of one rank."""

    rank: int
    tables: int
    live_param_bytes: int     # logical rows * width * itemsize placed here
    alloc_param_bytes: int    # the rank-uniform packed slab share
    opt_state_bytes: int
    a2a_buffer_bytes: int     # id block + fwd/bwd activation blocks
    total_bytes: int
    hbm_frac: float           # total / chip HBM
    # jit-carried streaming-vocab state (slot map + freq + admission
    # sketch per width slab with a dynamic table; parallel/streaming.py)
    # — rank-uniform like the slabs, 0 for fully-static plans
    streaming_state_bytes: int = 0
    # the online runtime's RCU double-buffer (parallel/online.py): two
    # param-slab copies live at the publish instant (published view +
    # in-flight clone), one frozen opt-shaped slab shared across
    # versions, and two streaming-state copies — 0 for offline plans
    snapshot_bytes: int = 0
    # the process-isolated serving transport (parallel/supervisor.py):
    # the double-buffered seqlock shared-memory region the trainer maps
    # to publish snapshots to out-of-process workers (utils/shm.py).
    # HOST RAM on the trainer host, not HBM — reported but excluded
    # from total_bytes / hbm_frac and the HBM contract. Rank-uniform
    # (the pickled payload carries the GLOBAL gathered slabs); 0 for
    # in-process plans.
    shm_region_bytes: int = 0

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SlabBudget:
    """One width slab's per-rank apply-scatter target."""

    width: int
    phys_rows: int
    phys_width: int
    rank_bytes: int
    cliff: str                # "sub_cliff" | "cliff_band" | "past_cliff"

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class PlanReport:
    """Everything the static model predicts about one plan at one
    (batch, optimizer, dtype) configuration, plus any contract
    violations. All byte figures are PER RANK unless suffixed
    ``_global``; a2a payloads are per rank per step (bytes leaving the
    chip — the same convention as the on-device ``*_a2a_bytes`` step
    metrics, so predictions are directly checkable against telemetry).
    """

    label: str
    chip: str
    world: int
    strategy: str
    dp_input: bool
    global_batch: int
    local_batch: int
    param_dtype: str
    comm_dtype: str
    optimizer: str
    n_tables: int
    n_sliced_tables: int
    n_groups: int             # padded-group shape count (recompile surface)
    l_max: int
    s_max: int
    groups: List[Dict[str, Any]]
    per_rank: List[RankBudget]
    slabs: List[SlabBudget]
    id_a2a_bytes_per_step: int
    out_a2a_bytes_per_step: int
    grad_a2a_bytes_per_step: int
    total_a2a_bytes_per_step: int
    imbalance_ratio: float
    out_pad_frac: float       # dead-column fraction of the padded exchange
    violations: List[str] = dataclasses.field(default_factory=list)
    n_streaming_tables: int = 0  # dynamic-vocab tables in the plan

    @property
    def ok(self) -> bool:
        return not self.violations

    @property
    def max_rank_bytes(self) -> int:
        return max(r.total_bytes for r in self.per_rank)

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return d

    def raise_on_violations(self) -> None:
        if self.violations:
            raise PlanAuditError(
                f"{self.label}: {len(self.violations)} plan-contract "
                "violation(s):\n  " + "\n  ".join(self.violations))

    def markdown(self) -> str:
        """Per-rank budget table + slab/cliff table, for docs and CLI."""
        lines = [
            f"### plan audit: {self.label}",
            "",
            f"chip {self.chip} · world {self.world} · strategy "
            f"{self.strategy} · batch {self.global_batch} (local "
            f"{self.local_batch}) · {self.param_dtype} params · "
            f"{self.optimizer} · {'dp' if self.dp_input else 'mp'} input",
            "",
            f"groups {self.n_groups} · l_max {self.l_max} · s_max "
            f"{self.s_max} · pad {self.out_pad_frac:.1%} · imbalance "
            f"{self.imbalance_ratio:.2f} · a2a/step "
            f"{self.total_a2a_bytes_per_step / 1e6:.2f} MB/rank"
            + (f" · {self.n_streaming_tables} streaming table(s), "
               f"{self.per_rank[0].streaming_state_bytes / 1e6:.2f} MB/rank "
               "slot-map+sketch state"
               if self.n_streaming_tables and self.per_rank else "")
            + (f" · online RCU snapshots "
               f"{self.per_rank[0].snapshot_bytes / 1e6:.2f} MB/rank"
               if self.per_rank and self.per_rank[0].snapshot_bytes
               else "")
            + (f" · shm serving region "
               f"{self.per_rank[0].shm_region_bytes / 1e6:.2f} MB host"
               if self.per_rank and self.per_rank[0].shm_region_bytes
               else ""),
            "",
            "| rank | tables | live GB | alloc GB | opt GB | a2a buf GB "
            "| total GB | HBM frac |",
            "|---:|---:|---:|---:|---:|---:|---:|---:|",
        ]
        for r in self.per_rank:
            lines.append(
                f"| {r.rank} | {r.tables} | {_gb(r.live_param_bytes):.3f} "
                f"| {_gb(r.alloc_param_bytes):.3f} "
                f"| {_gb(r.opt_state_bytes):.3f} "
                f"| {_gb(r.a2a_buffer_bytes):.3f} "
                f"| {_gb(r.total_bytes):.3f} | {r.hbm_frac:.1%} |")
        lines += ["", "| slab | phys shape | rank GB | cliff |",
                  "|---|---|---:|---|"]
        for s in self.slabs:
            lines.append(
                f"| w{s.width} | [{s.phys_rows}, {s.phys_width}] "
                f"| {_gb(s.rank_bytes):.3f} | {s.cliff} |")
        if self.violations:
            lines += ["", "violations:"] + [f"* {v}" for v in self.violations]
        return "\n".join(lines)


class PlanAuditError(RuntimeError):
    """Raised by :meth:`PlanReport.raise_on_violations` in strict use."""


# --------------------------------------------------------------------------
# contracts
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanContract:
    """Declarative limits a plan must satisfy before it is worth
    building. ``None`` fields are unchecked; :func:`default_contract`
    fills the HBM limit from the chip registry. Violation messages name
    the offending rank / slab so the fix (re-balance, slice, shrink) is
    actionable without re-deriving the report."""

    max_rank_bytes: Optional[int] = None
    max_a2a_bytes_per_step: Optional[int] = None
    max_groups: Optional[int] = DEFAULT_MAX_GROUPS
    forbid_cliff_slabs: bool = True
    require_every_rank_owns_a_table: bool = True
    reason: str = ""


def default_contract(chip: str = "v5e") -> PlanContract:
    """The make-verify contract: fit the chip's usable HBM, keep every
    rank populated, no apply slab past the measured scatter cliff,
    padded-group count within the zoo-scale invariant."""
    spec = CHIP_SPECS[chip]
    return PlanContract(
        max_rank_bytes=int(spec.hbm_bytes * spec.hbm_headroom),
        reason=f"fit {spec.name} ({_gb(spec.hbm_bytes):.0f} GiB HBM at "
               f"{spec.hbm_headroom:.0%} headroom)")


def check_contract(report: PlanReport, contract: PlanContract,
                   strategy=None) -> List[str]:
    """Evaluate one contract against a report; returns violation strings
    (empty = clean). Also appends them to ``report.violations``."""
    out: List[str] = []
    if contract.require_every_rank_owns_a_table and strategy is not None:
        empty = [r for r, tids in enumerate(strategy.table_ids_list)
                 if not tids]
        if empty:
            out.append(
                f"rank(s) {empty} own no table slice (world "
                f"{report.world} > {report.n_sliced_tables} sliced tables"
                " — DistributedEmbedding refuses such plans; shrink the "
                "world or slice the big tables)")
    if contract.max_rank_bytes is not None:
        for r in report.per_rank:
            if r.total_bytes > contract.max_rank_bytes:
                snap = (f" + online snapshots {_gb(r.snapshot_bytes):.2f}"
                        if r.snapshot_bytes else "")
                out.append(
                    f"rank {r.rank}: predicted {_gb(r.total_bytes):.2f} GB "
                    f"(params {_gb(r.alloc_param_bytes):.2f} + opt "
                    f"{_gb(r.opt_state_bytes):.2f} + a2a buffers "
                    f"{_gb(r.a2a_buffer_bytes):.2f}{snap}) exceeds the "
                    f"per-rank HBM contract "
                    f"{_gb(contract.max_rank_bytes):.2f} GB"
                    f" ({contract.reason or report.chip})")
    if contract.max_a2a_bytes_per_step is not None and \
            report.total_a2a_bytes_per_step > contract.max_a2a_bytes_per_step:
        out.append(
            f"per-rank a2a payload {report.total_a2a_bytes_per_step / 1e6:.1f}"
            f" MB/step exceeds the contract "
            f"{contract.max_a2a_bytes_per_step / 1e6:.1f} MB/step "
            f"(id {report.id_a2a_bytes_per_step / 1e6:.1f} + out "
            f"{report.out_a2a_bytes_per_step / 1e6:.1f} + grad "
            f"{report.grad_a2a_bytes_per_step / 1e6:.1f})")
    if contract.max_groups is not None and \
            report.n_groups > contract.max_groups:
        out.append(
            f"{report.n_groups} padded group shapes exceed the ceiling "
            f"{contract.max_groups} — the rank-uniform O(#groups) layout "
            "property is lost (compile surface grows with table "
            "heterogeneity)")
    if contract.forbid_cliff_slabs:
        for s in report.slabs:
            if s.cliff == "past_cliff":
                out.append(
                    f"slab w{s.width}: per-rank apply-scatter target "
                    f"{_gb(s.rank_bytes):.2f} GB is past the measured "
                    f"scatter cliff (>= "
                    f"{SCATTER_CLIFF_BYTES / 1e9:.2f} GB: 43→70 ms apply, "
                    "docs/perf_tpu.md) — split it with "
                    "column_slice_threshold or spread over more ranks")
    report.violations.extend(out)
    return out


# --------------------------------------------------------------------------
# the audit
# --------------------------------------------------------------------------


def audit_plan(target,
               global_batch: int,
               *,
               optimizer="sgd",
               param_dtype="float32",
               comm_dtype=None,
               id_dtype_bytes: int = 4,
               encodings: Optional[Sequence[tuple]] = None,
               cat_inputs: Optional[Sequence[Any]] = None,
               dp_input: Optional[bool] = None,
               chip: str = "v5e",
               label: Optional[str] = None,
               contract: Optional[PlanContract] = None,
               streaming_config=None,
               online: bool = False,
               isolated: bool = False) -> PlanReport:
    """Price a plan without building it.

    Args:
      target: a planned :class:`~..parallel.strategy.
        DistEmbeddingStrategy` or a built ``DistributedEmbedding`` (its
        strategy, ``dp_input`` and ``compute_dtype`` become defaults).
      global_batch: global batch size (divided over ``world`` ranks).
      optimizer: registry name (``sgd|adagrad|momentum|adam``), a
        ``Sparse*`` optimizer instance, or an :class:`OptimizerModel`.
      param_dtype / comm_dtype: slab dtype and exchanged-activation
        dtype (``None`` comm = the param dtype, matching the executor's
        ``compute_dtype=None`` default).
      encodings: explicit per-input exchange encodings (the
        ``("d", hot[, nslots])`` / ``("r"|"rw", cap)`` tuples of
        ``parallel/plan.py``). Defaults to hotness-1 dense for every
        input, or is derived from ``cat_inputs`` (global abstract/
        concrete arrays or Ragged-likes) when given.
      dp_input: whether the id all-to-all runs (``False`` = mp input,
        id exchange skipped — its payload prices at zero).
      contract: checked into ``report.violations`` when given
        (:func:`default_contract` is NOT applied implicitly — an audit
        is a report first, a gate only when asked).
      streaming_config: admission-sketch geometry for pricing
        streaming-table state — anything carrying ``.depth`` and
        ``.buckets`` (a :class:`~..parallel.streaming.StreamingConfig`;
        duck-typed so this module stays jax-free). Default: the
        ``DETPU_ADMIT_SKETCH_*`` env policy. Pass the SAME config the
        step builder gets via ``dynamic=`` or the per-rank
        ``streaming_state_bytes`` under-/over-bills a non-default
        sketch.
      online: price the concurrent train-and-serve runtime
        (``parallel/online.py``): bills the RCU snapshot double-buffer
        per rank as ``snapshot_bytes`` — two param-slab copies (the
        published view plus the in-flight clone at the publish
        instant), ONE opt-shaped frozen slab (the publisher clones
        optimizer state once and shares the buffers across every
        version — the serve forward never reads them), and two
        streaming-state copies. An offline-fitting plan can exceed HBM
        the moment serving runs beside training; this prices that
        before building anything.
      isolated: price the process-isolated serving transport
        (``parallel/supervisor.py``): bills the double-buffered seqlock
        shared-memory region as the rank-uniform ``shm_region_bytes``,
        using ``utils/shm.py``'s exact arithmetic —
        ``region_bytes(slack_capacity(payload))`` where the payload is
        the host-pickled GLOBAL snapshot (gathered packed slabs plus
        streaming leaves, world-wide; workers re-shard on ingest) and
        the slack is the ``DETPU_SHM_SLACK`` growth headroom. HOST RAM
        on the trainer host, not HBM: reported, but excluded from
        ``total_bytes`` / ``hbm_frac`` and the HBM contract.

    Nothing executes and nothing is materialized: the heaviest object
    built is the executor's numpy plan tensors (``[world, n]`` per
    group).
    """
    from ..parallel import plan as plan_mod  # numpy-only plan builder

    # a strategy exposes local_configs_list itself; a DistributedEmbedding
    # wraps one under .strategy (which on the strategy itself is the NAME)
    strategy = (target if hasattr(target, "local_configs_list")
                else target.strategy)
    if dp_input is None:
        dp_input = bool(getattr(target, "dp_input", True))
    if comm_dtype is None:
        comm_dtype = getattr(target, "compute_dtype", None) or param_dtype
    world = int(strategy.world_size)
    p_isz = _dtype_bytes(param_dtype)
    c_isz = _dtype_bytes(comm_dtype)
    model = optimizer_model(optimizer)

    if encodings is not None:
        encs = [tuple(e) for e in encodings]
        if global_batch % world:
            raise ValueError(
                f"global_batch {global_batch} not divisible by world {world}")
        b_local = global_batch // world
    elif cat_inputs is not None:
        encs, b_local = encodings_from_inputs(strategy, cat_inputs, world)
        if b_local * world != int(global_batch):
            raise ValueError(
                f"cat_inputs imply global batch {b_local * world}, "
                f"got global_batch={global_batch}")
    else:
        encs = [("d", 1)] * len(strategy.input_table_map)
        if global_batch % world:
            raise ValueError(
                f"global_batch {global_batch} not divisible by world {world}")
        b_local = global_batch // world

    geom = slab_geometry(strategy)
    plan = plan_mod.build_plan(strategy, [list(o) for o in
                                          geom.row_offsets_list],
                               encs, b_local)

    alloc_rank = geom.rank_param_bytes(p_isz)
    opt_rank = (model.slots * alloc_rank
                + model.aux_bytes_per_slab * len(geom.widths))

    # transient exchange buffers a step holds per rank: the id block
    # send+recv pair ([world, l_max] ids each; mp input holds one packed
    # block instead of a send/recv pair) and the output exchange's
    # forward send+recv pair ([world, b, s_max] activations; the
    # backward cotangent exchange reuses the same shapes after the
    # forward pair is dead, so it is not double-counted)
    id_blocks = 1 if not dp_input else 2
    a2a_buf = (id_blocks * world * plan.l_max * id_dtype_bytes
               + 2 * world * b_local * plan.s_max * c_isz)

    live_rank = [0] * world
    tables_rank = [0] * world
    for r, cfgs in enumerate(strategy.local_configs_list):
        tables_rank[r] = len(cfgs)
        for c in cfgs:
            live_rank[r] += int(c["input_dim"]) * int(c["output_dim"]) * p_isz

    # streaming-vocab carried state: slot map + frequency record (one
    # int32 each per logical slab row) + the admission sketch, for every
    # width slab holding a dynamic table (parallel/streaming.py). The
    # slab + shared-bucket ROWS are already priced above (a streaming
    # table declares input_dim = capacity + buckets); this is the extra
    # jit-carried state the dynamic mode adds to the per-rank HBM bill.
    stream_tids = [t for t, c in enumerate(strategy.global_configs)
                   if c.get("streaming")]
    stream_bytes = 0
    if stream_tids:
        if streaming_config is not None:
            depth = max(1, int(streaming_config.depth))
            buckets = max(2, int(streaming_config.buckets))
        else:
            from ..utils import envvars

            depth = max(1, envvars.get_int("DETPU_ADMIT_SKETCH_DEPTH"))
            buckets = max(2, envvars.get_int("DETPU_ADMIT_SKETCH_WIDTH"))
        for w in sorted({int(strategy.global_configs[t]["output_dim"])
                         for t in stream_tids}):
            rows = geom.phys_cap[w] * _pack_factor(w)
            stream_bytes += 2 * rows * 4 + depth * buckets * 4

    # the online runtime's RCU double-buffer (see the `online` arg):
    # 2x params (published + in-flight) + 1x opt (frozen, shared) +
    # 2x streaming state — exactly what SnapshotPublisher keeps live
    snap_bytes = (2 * alloc_rank + opt_rank + 2 * stream_bytes
                  if online else 0)

    # the process-isolated serving transport (see the `isolated` arg):
    # shm.py's exact region arithmetic over the host-pickled GLOBAL
    # payload — the gathered packed slabs plus streaming leaves across
    # every rank (the supervisor publishes global state; the worker
    # re-shards on ingest)
    shm_bytes = 0
    if isolated:
        from ..utils import shm as shm_mod

        payload_len = world * (alloc_rank + stream_bytes)
        shm_bytes = shm_mod.region_bytes(
            shm_mod.slack_capacity(payload_len))

    spec = CHIP_SPECS[chip]
    per_rank = []
    for r in range(world):
        total = alloc_rank + opt_rank + a2a_buf + stream_bytes + snap_bytes
        per_rank.append(RankBudget(
            rank=r, tables=tables_rank[r],
            live_param_bytes=live_rank[r],
            alloc_param_bytes=alloc_rank,
            opt_state_bytes=opt_rank,
            a2a_buffer_bytes=a2a_buf,
            total_bytes=total,
            hbm_frac=total / spec.hbm_bytes,
            streaming_state_bytes=stream_bytes,
            snapshot_bytes=snap_bytes,
            shm_region_bytes=shm_bytes))

    slabs = []
    for w in geom.widths:
        rb = geom.phys_cap[w] * geom.phys_w[w] * p_isz
        cliff = ("past_cliff" if rb >= SCATTER_CLIFF_BYTES
                 else "cliff_band" if rb > SCATTER_CLIFF_SAFE_BYTES
                 else "sub_cliff")
        slabs.append(SlabBudget(
            width=w, phys_rows=geom.phys_cap[w], phys_width=geom.phys_w[w],
            rank_bytes=rb, cliff=cliff))

    # per-step off-chip payloads — the exact step_metrics formulas, so
    # the prediction is checkable against the on-device *_a2a_bytes
    off = max(world - 1, 0)
    id_a2a = off * plan.l_max * id_dtype_bytes if dp_input else 0
    out_a2a = off * b_local * plan.s_max * c_isz
    live_cols = sum(plan.out_width(inst) for inst in plan.instances)
    pad_frac = (1.0 - live_cols / (world * plan.s_max)
                if plan.s_max else 0.0)
    mean_live = sum(live_rank) / world if world else 0.0
    imbalance = (max(live_rank) / mean_live) if mean_live else float("inf")

    n_sliced = sum(len(t) for t in strategy.table_ids_list)
    report = PlanReport(
        label=label or f"{strategy.strategy}/world{world}",
        chip=chip, world=world, strategy=strategy.strategy,
        dp_input=bool(dp_input), global_batch=int(global_batch),
        local_batch=b_local,
        param_dtype=_dtype_name(param_dtype),
        comm_dtype=_dtype_name(comm_dtype),
        optimizer=model.name,
        n_tables=len(strategy.global_configs),
        n_sliced_tables=n_sliced,
        n_groups=len(plan.groups), l_max=plan.l_max, s_max=plan.s_max,
        groups=[{"kind": g.kind, "width": g.width, "hot": g.hot,
                 "slots": g.n, "block_len": g.blen} for g in plan.groups],
        per_rank=per_rank, slabs=slabs,
        id_a2a_bytes_per_step=int(id_a2a),
        out_a2a_bytes_per_step=int(out_a2a),
        grad_a2a_bytes_per_step=int(out_a2a),
        total_a2a_bytes_per_step=int(id_a2a + 2 * out_a2a),
        imbalance_ratio=float(imbalance),
        out_pad_frac=float(pad_frac),
        n_streaming_tables=len(stream_tids))
    if contract is not None:
        check_contract(report, contract, strategy=strategy)
    return report


def audit_plan_spec(spec: Dict[str, Any],
                    *,
                    optimizer="sgd",
                    param_dtype="float32",
                    chip: str = "v5e",
                    contract: Optional[PlanContract] = None,
                    label: Optional[str] = None) -> PlanReport:
    """Capacity-only audit of a bare :meth:`DistEmbeddingStrategy.
    plan_spec` dict (e.g. read back from a checkpoint's ``meta.json``).
    The spec carries slice geometry but no input routing, so exchange
    payloads/groups price at zero — HBM and cliff contracts still
    apply (pair with :func:`audit_plan` for the full model)."""

    class _SpecView:
        """Duck-typed strategy view over the spec's ``local_tables``."""

        def __init__(self, s):
            self.world_size = int(s["world_size"])
            self.strategy = s.get("strategy", "?")
            self.local_configs_list = [
                [{"input_dim": rows, "output_dim": width}
                 for (_tid, rows, width, _rb, _cs) in rank]
                for rank in s["local_tables"]]
            self.table_ids_list = [[t[0] for t in rank]
                                   for rank in s["local_tables"]]
            self.global_configs = [None] * (max(
                (t[0] for rank in s["local_tables"] for t in rank),
                default=-1) + 1)
            self.input_table_map = []

    view = _SpecView(spec)
    world = view.world_size
    geom = slab_geometry(view)
    p_isz = _dtype_bytes(param_dtype)
    model = optimizer_model(optimizer)
    alloc_rank = geom.rank_param_bytes(p_isz)
    opt_rank = (model.slots * alloc_rank
                + model.aux_bytes_per_slab * len(geom.widths))
    chip_spec = CHIP_SPECS[chip]
    live_rank = [sum(int(c["input_dim"]) * int(c["output_dim"]) * p_isz
                     for c in cfgs) for cfgs in view.local_configs_list]
    per_rank = [RankBudget(
        rank=r, tables=len(view.local_configs_list[r]),
        live_param_bytes=live_rank[r], alloc_param_bytes=alloc_rank,
        opt_state_bytes=opt_rank, a2a_buffer_bytes=0,
        total_bytes=alloc_rank + opt_rank,
        hbm_frac=(alloc_rank + opt_rank) / chip_spec.hbm_bytes)
        for r in range(world)]
    slabs = []
    for w in geom.widths:
        rb = geom.phys_cap[w] * geom.phys_w[w] * p_isz
        cliff = ("past_cliff" if rb >= SCATTER_CLIFF_BYTES
                 else "cliff_band" if rb > SCATTER_CLIFF_SAFE_BYTES
                 else "sub_cliff")
        slabs.append(SlabBudget(w, geom.phys_cap[w], geom.phys_w[w], rb,
                                cliff))
    mean_live = sum(live_rank) / world if world else 0.0
    report = PlanReport(
        label=label or f"spec/{view.strategy}/world{world}",
        chip=chip, world=world, strategy=view.strategy, dp_input=True,
        global_batch=0, local_batch=0,
        param_dtype=_dtype_name(param_dtype),
        comm_dtype=_dtype_name(param_dtype),
        optimizer=model.name, n_tables=len(view.global_configs),
        n_sliced_tables=sum(len(t) for t in view.table_ids_list),
        n_groups=0, l_max=0, s_max=0, groups=[], per_rank=per_rank,
        slabs=slabs, id_a2a_bytes_per_step=0, out_a2a_bytes_per_step=0,
        grad_a2a_bytes_per_step=0, total_a2a_bytes_per_step=0,
        imbalance_ratio=(max(live_rank) / mean_live) if mean_live
        else float("inf"),
        out_pad_frac=0.0)
    if contract is not None:
        # exchange/group limits are unknowable from a bare spec
        capacity_only = dataclasses.replace(
            contract, max_a2a_bytes_per_step=None, max_groups=None)
        check_contract(report, capacity_only, strategy=view)
    return report


# --------------------------------------------------------------------------
# calibration + planner ranking
# --------------------------------------------------------------------------


def compare_with_memory(report: PlanReport,
                        mem_report: Dict[str, Any]) -> Dict[str, Any]:
    """Drift of the jax-free byte model against
    :func:`.memory.table_memory_report`'s ``eval_shape`` accounting (the
    calibration target). Returns fractional drifts per component plus
    ``max_abs_drift``; the CLI's strict mode requires ~exact agreement
    (the two compute the same layout — drift means the mirror broke)."""
    totals = mem_report["totals"]
    world = mem_report["world"]

    def drift(pred, target):
        if not target:
            return 0.0 if not pred else float("inf")
        return (pred - target) / target

    pred_alloc = sum(r.alloc_param_bytes for r in report.per_rank)
    pred_live = sum(r.live_param_bytes for r in report.per_rank)
    pred_opt = sum(r.opt_state_bytes for r in report.per_rank)
    out = {
        "param_alloc_drift": drift(pred_alloc,
                                   totals["param_bytes_allocated"]),
        "param_live_drift": drift(pred_live, totals["param_bytes_live"]),
        "opt_state_drift": (
            drift(pred_opt, totals["opt_state_bytes"])
            if totals.get("opt_state_bytes") is not None else 0.0),
        "world": world,
    }
    out["max_abs_drift"] = max(abs(v) for k, v in out.items()
                               if k.endswith("_drift"))
    return out


def price_int8_serving(target,
                       global_batch: int,
                       *,
                       param_dtype="float32",
                       comm_dtype=None,
                       scale_bytes: int = 4,
                       chip: str = "v5e",
                       encodings: Optional[Sequence[tuple]] = None,
                       cat_inputs: Optional[Sequence[Any]] = None,
                       dp_input: Optional[bool] = None,
                       label: Optional[str] = None) -> Dict[str, Any]:
    """Price an int8-rows-with-per-row-scales SERVING variant of a plan
    — pricing only, nothing materializes (the quantized table itself is
    a future PR; this is its capacity case and the input to the hot-row
    cache sizing of ROADMAP item 1).

    The variant: frozen inference tables store each logical row as
    ``width`` int8 codes plus one ``scale_bytes``-wide per-row scale,
    dequantized after the gather. Two effects priced here:

    * **per-rank HBM** — the serving table bill drops from
      ``rows x width x itemsize`` to ``rows x (width + scale_bytes)``
      (~4x for fp32 tables, ~2x for bf16, minus the per-row scale tax
      that bites narrow widths hardest); no optimizer state exists at
      serve time, so tables ARE the resident bill.
    * **out-a2a payload** — when the exchange ships the quantized rows
      (one scale per routed slot) and dequantizes on the receiving
      side, the activation payload shrinks by the same code/scale
      arithmetic — fewer off-chip bytes per request on exactly the
      exchange the serving runtime's latency rides.

    Returns a plain JSON-able dict next to a baseline
    :func:`audit_plan` run (optimizer ``"sgd"`` — zero slots, the
    inference bill). Keyed so ``tools/serve_bench.py`` can embed it in
    the bench ``serving`` section.
    """
    strategy = (target if hasattr(target, "local_configs_list")
                else target.strategy)
    base = audit_plan(target, global_batch, optimizer="sgd",
                      param_dtype=param_dtype, comm_dtype=comm_dtype,
                      encodings=encodings, cat_inputs=cat_inputs,
                      dp_input=dp_input, chip=chip, label=label)
    p_isz = _dtype_bytes(param_dtype)
    c_isz = _dtype_bytes(base.comm_dtype)
    geom = slab_geometry(strategy)
    base_table = geom.rank_param_bytes(p_isz)
    int8_table = sum(geom.rows_cap[w] * (w + scale_bytes)
                     for w in geom.widths)
    # one scale per routed (sample, slot) pair rides the quantized
    # exchange next to the int8 codes; s_max counts padded columns, the
    # group slot counts the scales
    n_slots = sum(g["slots"] for g in base.groups)
    off = max(base.world - 1, 0)
    int8_out = off * base.local_batch * (base.s_max + n_slots * scale_bytes)
    spec = CHIP_SPECS[chip]
    return {
        "label": base.label,
        "world": base.world,
        "param_dtype": base.param_dtype,
        "scale_bytes": int(scale_bytes),
        "table_bytes_per_rank": int(base_table),
        "int8_table_bytes_per_rank": int(int8_table),
        "table_bytes_ratio": (base_table / int8_table
                              if int8_table else 0.0),
        "hbm_frac": base_table / spec.hbm_bytes,
        "int8_hbm_frac": int8_table / spec.hbm_bytes,
        "out_a2a_bytes_per_step": int(base.out_a2a_bytes_per_step),
        "int8_out_a2a_bytes_per_step": int(int8_out),
        "out_a2a_ratio": (base.out_a2a_bytes_per_step / int8_out
                          if int8_out else 0.0),
        "comm_dtype_bytes": int(c_isz),
        "note": "pricing only — the quantized serving table is a "
                "future PR; dequantize-after-gather assumed, "
                "one scale per logical row / per routed slot",
    }


def rank_strategies(configs,
                    world: int,
                    global_batch: int,
                    strategies: Sequence[str] = ("basic", "memory_balanced",
                                                 "memory_optimized",
                                                 "comm_balanced"),
                    column_slice_threshold: Optional[int] = None,
                    row_slice_threshold: Optional[int] = None,
                    input_table_map=None,
                    input_hotness=None,
                    **audit_kw) -> List[Tuple[str, PlanReport]]:
    """Plan every candidate strategy and rank them by predicted cost —
    the planner-side cost hook (``telemetry_balanced`` is excluded by
    default: it needs measured ``table_loads``).

    Sort key: contract-violating plans last, then max per-rank bytes,
    then total a2a payload — "fits first, cheapest exchange among those
    that fit". Returns ``[(strategy_name, PlanReport)]`` best first.
    """
    from ..parallel.strategy import DistEmbeddingStrategy

    contract = audit_kw.pop("contract", None)
    out = []
    for name in strategies:
        st = DistEmbeddingStrategy(
            configs, world, strategy=name,
            input_table_map=input_table_map,
            column_slice_threshold=column_slice_threshold,
            row_slice_threshold=row_slice_threshold,
            input_hotness=input_hotness)
        rep = audit_plan(st, global_batch, label=f"{name}/world{world}",
                         contract=contract, **audit_kw)
        out.append((name, rep))
    out.sort(key=lambda kv: (len(kv[1].violations),
                             kv[1].max_rank_bytes,
                             kv[1].total_a2a_bytes_per_step))
    return out


def report_to_jsonl(report: PlanReport) -> str:
    """One-line JSON form (sidecar-friendly)."""
    return json.dumps(report.to_json(), sort_keys=True)

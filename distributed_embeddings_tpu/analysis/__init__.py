"""Static analysis of compiled SPMD train steps.

The hybrid-parallel design is a *communication contract* — exactly one id
all-to-all and one output all-to-all forward, one cotangent all-to-all
backward — and this package verifies it by abstract interpretation
(jaxpr/StableHLO inspection, no backend execution) instead of by reading
throughput numbers after the fact. See :mod:`.audit`.
"""

from .audit import (
    AuditError,
    AuditReport,
    CollectiveRecord,
    audit_step_fn,
    audit_train_step,
    expected_collectives,
)

__all__ = [
    "AuditError",
    "AuditReport",
    "CollectiveRecord",
    "audit_step_fn",
    "audit_train_step",
    "expected_collectives",
]

"""Static analysis + telemetry of compiled SPMD train steps.

The hybrid-parallel design is a *communication contract* — exactly one id
all-to-all and one output all-to-all forward, one cotangent all-to-all
backward — and this package verifies it by abstract interpretation
(jaxpr/StableHLO inspection, no backend execution) instead of by reading
throughput numbers after the fact. See :mod:`.audit`.

Three sibling layers complete the observatory: :mod:`.hlo_census` (the
per-phase op census of the *optimized HLO* — gather/scatter/sort/convert
pass budgets per ``obs.scope`` phase, enforced by ``tools/hlo_audit.py``),
:mod:`.telemetry` (on-device jit-carried access telemetry — per-table
hot-row sketches, per-rank load accounting) and :mod:`.memory` (static
per-table/slab HBM budgets plus compiled-step memory/FLOP reports via
abstract lowering). Fused into one run report by ``tools/obs_report.py``.
"""

from .audit import (
    AuditError,
    AuditReport,
    CollectiveRecord,
    audit_step_fn,
    audit_train_step,
    expected_collectives,
)
from .hlo_census import (
    CensusError,
    CensusReport,
    PassBudget,
    census_of_text,
    census_step_fn,
    census_train_step,
    dedup_zero_contracts,
    default_contracts,
)
from .memory import (
    compiled_step_report,
    step_memory_report,
    table_memory_report,
)
from .telemetry import (
    TelemetryConfig,
    hot_rows,
    init_telemetry,
    load_balance,
    summarize_telemetry,
    telemetry_enabled,
)

__all__ = [
    "AuditError",
    "AuditReport",
    "CollectiveRecord",
    "audit_step_fn",
    "audit_train_step",
    "expected_collectives",
    "TelemetryConfig",
    "init_telemetry",
    "hot_rows",
    "load_balance",
    "summarize_telemetry",
    "telemetry_enabled",
    "table_memory_report",
    "compiled_step_report",
    "step_memory_report",
    "CensusError",
    "CensusReport",
    "PassBudget",
    "census_of_text",
    "census_step_fn",
    "census_train_step",
    "dedup_zero_contracts",
    "default_contracts",
]

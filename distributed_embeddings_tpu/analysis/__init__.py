"""Static analysis + telemetry of compiled SPMD train steps.

The hybrid-parallel design is a *communication contract* — exactly one id
all-to-all and one output all-to-all forward, one cotangent all-to-all
backward — and this package verifies it by abstract interpretation
(jaxpr/StableHLO inspection, no backend execution) instead of by reading
throughput numbers after the fact. See :mod:`.audit`.

Four sibling layers complete the observatory: :mod:`.hlo_census` (the
per-phase op census of the *optimized HLO* — gather/scatter/sort/convert
pass budgets per ``obs.scope`` phase, enforced by ``tools/hlo_audit.py``),
:mod:`.telemetry` (on-device jit-carried access telemetry — per-table
hot-row sketches, per-rank load accounting), :mod:`.memory` (static
per-table/slab HBM budgets plus compiled-step memory/FLOP reports via
abstract lowering) and :mod:`.plan_audit` (the gate BEFORE all of the
above: a backend-free byte/comms model of a placement plan, enforced as
:class:`~.plan_audit.PlanContract` s by ``tools/plan_audit.py`` — incl.
the chip capacity registry). Fused into one run report by
``tools/obs_report.py``.

:mod:`.schedule_audit` sees what none of the above can: the DEPENDENCY
STRUCTURE of the optimized step. It parses operands out of the compiled
HLO, builds the full dependency DAG, prices every node under a
bytes-based cost model (chips from :data:`~.plan_audit.CHIP_SPECS`),
computes the critical path, and classifies each collective as
serialized-on or overlappable-with dense compute — enforced as
:class:`~.schedule_audit.ScheduleContract` s and as the
:class:`~..parallel.schedule.StepSchedule` declaration check by
``tools/schedule_audit.py --strict`` (= ``make schedule-audit``).

:mod:`.phase_profile` is the MEASURED counterpart of all of the above:
it runs N timed steps under ``jax.profiler.trace``, attributes every
op-level trace event to its ``obs.scope`` phase (via the jax-free
``utils/traceparse.py`` parser + the compiled module's own
``metadata.op_name`` text), reduces them to a
:class:`~.phase_profile.PhaseProfile` (per-phase p50/p95 ms, measured
exchange/lookup/apply/dense breakdown, measured a2a and overlap
fractions), calibrates the schedule auditor's byte-cost model against
the clock (:func:`~.phase_profile.calibrate` drift table), and
cross-checks the measured vs modeled serialized/overlappable
classification (:func:`~.phase_profile.check_agreement`) — enforced by
``tools/phase_profile.py --strict`` (= ``make phase-profile``).

:mod:`.concurrency_audit` guards the one axis the compiled-step auditors
never see: HOST-SIDE concurrency. Half one is a jax-free AST
lock-discipline analysis of the serving plane (threads-of-control
discovery, shared attributes mutated from two+ threads without a
dominating lock, the lock-acquisition-order graph with cycle detection,
blocking calls under a held lock, declarative
:class:`~.concurrency_audit.ConcurrencyContract` s); half two is an
explicit-state interleaving model checker that proves the shm seqlock's
torn-read detection and the supervisor heartbeat's rid monotonicity
over the full bounded interleaving space while refuting three seeded
mutants — enforced by ``tools/concurrency_audit.py --strict``
(= ``make concurrency-audit``).
"""

from .audit import (
    AuditError,
    AuditReport,
    CollectiveRecord,
    audit_step_fn,
    audit_train_step,
    expected_collectives,
)
from .hlo_census import (
    CensusError,
    CensusReport,
    PassBudget,
    census_of_text,
    census_step_fn,
    census_train_step,
    dedup_zero_contracts,
    default_contracts,
)
from .memory import (
    compiled_step_report,
    step_memory_report,
    table_memory_report,
)
from .plan_audit import (
    CHIP_SPECS,
    ChipSpec,
    PlanAuditError,
    PlanContract,
    PlanReport,
    audit_plan,
    audit_plan_spec,
    compare_with_memory,
    default_contract,
    rank_strategies,
)
# .audit also defines an AuditReport, so the concurrency report class is
# reached via the submodule (concurrency_audit.AuditReport); only the
# collision-free names are re-exported flat
from . import concurrency_audit
from .concurrency_audit import (
    ConcFinding,
    ConcurrencyContract,
    ProofResult,
    audit_repo,
    audit_source,
    prove,
    refute,
    seqlock_model,
    supervisor_model,
)
from . import phase_profile
from .phase_profile import (
    CalibrationReport,
    HloPhaseIndex,
    PhaseProfile,
    PhaseProfileError,
    calibrate,
    check_agreement,
    profile_steps,
)
from . import schedule_audit
from .schedule_audit import (
    CollectiveInfo,
    ScheduleContract,
    ScheduleGraph,
    ScheduleGraphError,
    ScheduleReport,
    baseline_contracts,
    parse_hlo_module,
)
from .telemetry import (
    TelemetryConfig,
    hot_rows,
    init_telemetry,
    load_balance,
    summarize_telemetry,
    telemetry_enabled,
)

__all__ = [
    "AuditError",
    "AuditReport",
    "CollectiveRecord",
    "audit_step_fn",
    "audit_train_step",
    "expected_collectives",
    "TelemetryConfig",
    "init_telemetry",
    "hot_rows",
    "load_balance",
    "summarize_telemetry",
    "telemetry_enabled",
    "table_memory_report",
    "compiled_step_report",
    "step_memory_report",
    "CalibrationReport",
    "HloPhaseIndex",
    "PhaseProfile",
    "PhaseProfileError",
    "calibrate",
    "check_agreement",
    "profile_steps",
    "CensusError",
    "CensusReport",
    "PassBudget",
    "census_of_text",
    "census_step_fn",
    "census_train_step",
    "dedup_zero_contracts",
    "default_contracts",
    "CHIP_SPECS",
    "ChipSpec",
    "PlanAuditError",
    "PlanContract",
    "PlanReport",
    "audit_plan",
    "audit_plan_spec",
    "compare_with_memory",
    "default_contract",
    "rank_strategies",
    "schedule_audit",
    "CollectiveInfo",
    "ScheduleContract",
    "ScheduleGraph",
    "ScheduleGraphError",
    "ScheduleReport",
    "baseline_contracts",
    "parse_hlo_module",
    "concurrency_audit",
    "ConcFinding",
    "ConcurrencyContract",
    "ProofResult",
    "audit_repo",
    "audit_source",
    "prove",
    "refute",
    "seqlock_model",
    "supervisor_model",
]

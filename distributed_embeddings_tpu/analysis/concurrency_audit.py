"""Concurrency auditor — lock discipline + interleaving model checking.

The fifth static gate (``make concurrency-audit``). The other four
gates — detlint, the jaxpr collective census, the HLO pass budgets, the
schedule DAG — all verify the *jitted step*; none can see the host-side
control plane that PRs 16-18 grew around it: the ``RealtimeDriver``
arrival thread, the ``Supervisor``'s monitor/sender/accept threads, the
lock-free seqlock in ``utils/shm.py`` and the thread-shared ``mplane``
registry. This module covers exactly that layer, in two halves, both
jax-free (pure AST + explicit-state search, no backend, no wall time).

Half 1 — lock-discipline analysis (AST)
---------------------------------------
:func:`scan_module` discovers the *threads of control* per module
(``threading.Thread(target=...)`` sites — ``self``-method, nested
function and lambda targets — ``run()`` overrides of ``Thread``
subclasses, ``do_*`` handlers of HTTP request handler classes and
spawn-context ``Process`` entry points), builds per-class attribute
access maps with the lock context of every site, and
:func:`audit_modules` reports:

* ``unguarded-shared`` — an attribute mutated without a dominating
  ``with self._lock:`` while ≥ 2 threads of control access it (or while
  the class declares it in ``_THREAD_SHARED``);
* ``lock-order-cycle`` — a cycle (incl. self-loops: two instances of
  one class) in the global lock-acquisition-order graph, with
  acquisitions propagated through intra-module calls;
* ``blocking-under-lock`` — ``time.sleep``, ``Queue.get/put`` without a
  timeout, ``.join()``/``.wait()`` without a timeout or a subprocess
  wait executed while a lock is held (direct, or bubbled up through
  intra-class calls);
* ``global-unguarded`` — a contract-declared shared module global
  mutated outside any module-level lock;
* ``contract-drift`` — the discovered thread inventory disagrees with
  the module's declared :class:`ConcurrencyContract`, or a
  ``_THREAD_SHARED`` tuple names an attribute that does not exist.

Deliberate lock-free sites carry line waivers, matching the detlint
comment conventions::

    self._worker = None   # thread-local-ok: atomic reference swap ...
    with second._lock:    # lock-order-ok: id-ordered acquisition ...
    conn.recv()           # blocking-ok: heartbeat-bounded ...

and every concurrent module declares a :class:`ConcurrencyContract`
(additive, like ``PassBudget``/``PlanContract``): its threads of
control, the *external* thread roots of its classes (e.g. the online
runtime drives ``ServingRuntime.submit`` from the realtime-driver
thread while the trainer thread installs snapshots — invisible to a
per-class analysis without the declaration), and its shared module
globals. Drift between declaration and code is itself a finding.

Half 2 — interleaving model checker
-----------------------------------
The two hand-rolled synchronization protocols are extracted into small
explicit-state transition systems and *exhaustively* explored
(:func:`explore`: BFS over every interleaving, virtual clock, bounded
depth, no wall time), proving what the chaos drills only spot-check:

* :func:`seqlock_model` — the ``utils/shm.py`` writer/reader at word
  granularity (header pack → payload words → end-stamp → latest flip
  vs. read-latest → read-header → copy words → CRC verify). Invariants:
  every torn or lapped read is *detected* (never returned as data), a
  buffer that claims completeness (``begin == end``) really holds that
  publication's complete payload + CRC ("stamp honesty" — what makes
  the stamp fast-path meaningful), the writer is never blocked by any
  reader state, and reader retries stay bounded.
* :func:`supervisor_model` — the heartbeat state machine of
  ``parallel/supervisor.py`` (alive → missed-deadline → kill+restart →
  re-ingest) round-based against nondeterministic crash/hang faults.
  Invariants: request conservation (every rid answered exactly once:
  served + unavailable == answered), rid monotonicity across restarts,
  a hang is detected within the declared deadline, snapshot publication
  is enabled in *every* reachable state (never blocks on a dead
  worker), the restart budget is respected and a reborn worker's
  ingested snapshot never regresses.

Three seeded protocol mutants must be *refuted* by the same explorer
(:data:`MUTANTS`): ``seqlock:no_crc`` (CRC check removed — a lapped
torn copy is then accepted), ``seqlock:stamps_swapped`` (the end-stamp
written up-front with the header — the buffer lies about completeness)
and ``supervisor:deadline_off_by_one`` (hang detection one heartbeat
late). The CLI self-drills all three plus the Half-1 drill sources
(:func:`run_drills`), like ``schedule_audit``'s fake-overlap drill.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import os
import posixpath
from typing import (Any, Callable, Dict, FrozenSet, Iterable, List, Mapping,
                    Optional, Sequence, Set, Tuple)

__all__ = [
    "BLOCKING_OK",
    "ConcFinding",
    "ConcurrencyContract",
    "LOCK_ORDER_OK",
    "MUTANTS",
    "Model",
    "ProofResult",
    "REFERENCE_CONTRACTS",
    "THREAD_LOCAL_OK",
    "AuditReport",
    "audit_modules",
    "audit_repo",
    "audit_source",
    "explore",
    "package_root",
    "prove",
    "refute",
    "run_drills",
    "scan_module",
    "seqlock_model",
    "supervisor_model",
]

# ----------------------------------------------------------- waiver idioms

#: waives an ``unguarded-shared``/``global-unguarded`` mutation site
THREAD_LOCAL_OK = "thread-local-ok:"
#: waives a lock acquisition's contribution to the order graph
LOCK_ORDER_OK = "lock-order-ok:"
#: waives a blocking call site (direct or the call that bubbles one up)
BLOCKING_OK = "blocking-ok:"

#: constructor names whose instances ARE mutual-exclusion locks — a
#: ``with self.<attr>:`` over one of these is a guard + a graph node
LOCK_TYPES = {"Lock", "RLock", "Condition"}

#: constructor names whose instances synchronize internally — mutating
#: method calls on such attributes are not shared-state findings
#: (QuantileSketch/MetricsRegistry/FlightRecorder are documented
#: thread-safe in utils/mplane.py; TraceBuffer holds one internal lock
#: around its active table + retained ring in utils/reqtrace.py;
#: ``local`` is threading.local)
SYNCHRONIZED_TYPES = LOCK_TYPES | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "QuantileSketch", "MetricsRegistry", "FlightRecorder",
    "TraceBuffer", "local",
}

#: method names that mutate their receiver in place
MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse", "put", "put_nowait",
}

#: subprocess-module waits (blocking when called without ``timeout=``)
SUBPROCESS_WAITS = {"run", "call", "check_call", "check_output"}


@dataclasses.dataclass(frozen=True)
class ConcFinding:
    """One auditor finding, detlint-shaped: ``path:line: [kind] msg``."""

    kind: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.kind}] {self.message}"


# ====================================================================
# Half 1 — AST scanning
# ====================================================================


def _type_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_self(node: ast.AST) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


@dataclasses.dataclass
class Access:
    attr: str
    kind: str                  # "write" | "mutate" | "read"
    line: int
    locks: FrozenSet[str]
    unit: str
    waived: bool


@dataclasses.dataclass
class Acquisition:
    lock: str
    line: int
    held: FrozenSet[str]
    unit: str
    waived: bool


@dataclasses.dataclass
class Blocking:
    desc: str
    line: int
    locks: FrozenSet[str]
    unit: str
    waived: bool


@dataclasses.dataclass
class CallSite:
    callee: Tuple[str, str]    # ("self", meth) | ("mod", func)
    line: int
    locks: FrozenSet[str]
    unit: str
    waived: bool               # BLOCKING_OK on the call line


@dataclasses.dataclass
class Spawn:
    ident: str                 # canonical thread-of-control id
    line: int
    kind: str                  # "thread" | "process" | "handler"
    entry_unit: Optional[str]  # unit name running on that thread


@dataclasses.dataclass
class UnitScan:
    """Everything collected from one unit of execution (a method, or a
    nested function/lambda that runs on its own spawned thread)."""

    name: str
    accesses: List[Access] = dataclasses.field(default_factory=list)
    acquisitions: List[Acquisition] = dataclasses.field(default_factory=list)
    blockings: List[Blocking] = dataclasses.field(default_factory=list)
    calls: List[CallSite] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassScan:
    name: str                  # qualified (nesting joined with ".")
    line: int
    bases: Tuple[str, ...]
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: the subset of lock_attrs that are reentrant (threading.RLock):
    #: a self-edge on one of these is re-acquisition, not deadlock
    rlock_attrs: Set[str] = dataclasses.field(default_factory=set)
    sync_attrs: Set[str] = dataclasses.field(default_factory=set)
    thread_shared: Optional[Tuple[str, ...]] = None
    thread_shared_line: int = 0
    units: Dict[str, UnitScan] = dataclasses.field(default_factory=dict)
    #: unit name -> canonical root id (thread entries, handlers)
    entries: Dict[str, str] = dataclasses.field(default_factory=dict)
    spawns: List[Spawn] = dataclasses.field(default_factory=list)
    #: method name -> unit names (properties/setters share a name)
    by_name: Dict[str, List[str]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleScan:
    path: str
    lines: List[str]
    classes: List[ClassScan] = dataclasses.field(default_factory=list)
    #: module-level function units, keyed by function name
    funcs: Dict[str, UnitScan] = dataclasses.field(default_factory=dict)
    module_locks: Set[str] = dataclasses.field(default_factory=set)
    spawns: List[Spawn] = dataclasses.field(default_factory=list)
    #: watched module globals -> mutation Access list
    global_accesses: List[Access] = dataclasses.field(default_factory=list)


_STMT_BLOCKS = ("body", "orelse", "finalbody")


class _Scanner:
    """Scans one unit of execution with a lexical lock-context stack."""

    def __init__(self, mscan: ModuleScan, cls: Optional[ClassScan],
                 unit: UnitScan, module_funcs: Set[str],
                 class_methods: Set[str], skip_nodes: Set[ast.AST],
                 watch_globals: Set[str]):
        self.m = mscan
        self.cls = cls
        self.unit = unit
        self.module_funcs = module_funcs
        self.class_methods = class_methods
        self.skip = skip_nodes
        self.watch = watch_globals

    # ------------------------------------------------------------ helpers

    def _marked(self, line: int, marker: str) -> bool:
        idx = line - 1
        return 0 <= idx < len(self.m.lines) and marker in self.m.lines[idx]

    def _lock_id(self, expr: ast.expr) -> Optional[str]:
        """Resolve a ``with`` context expression to a lock identity.

        ``self.<attr>`` (or ``<anything>.<attr>`` when ``attr`` is a
        known lock attribute of the *current* class — the id-ordered
        two-instance idiom) maps to ``Class.attr``; a bare module-level
        lock name maps to ``module:name``. Everything else (files,
        sockets, tempdirs) is not a lock."""
        if isinstance(expr, ast.IfExp):
            # `second._lock if first is not second else _NULL_CTX`:
            # conservatively treat a conditional acquisition as
            # acquiring whichever branch resolves to a lock
            return (self._lock_id(expr.body)
                    or self._lock_id(expr.orelse))
        if isinstance(expr, ast.Attribute):
            if self.cls is not None and expr.attr in self.cls.lock_attrs:
                return f"{self.cls.name}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) and expr.id in self.m.module_locks:
            return f"{posixpath.basename(self.m.path)}:{expr.id}"
        return None

    # ------------------------------------------------------- entry points

    def scan_function(self, fnode: ast.AST) -> None:
        if isinstance(fnode, ast.Lambda):
            self._expr(fnode.body, frozenset())
        else:
            self._block(fnode.body, frozenset())

    def scan_bodies(self, nodes: Iterable[ast.AST]) -> None:
        for n in nodes:
            self.scan_function(n)

    # ------------------------------------------------------ statement walk

    def _block(self, stmts: Sequence[ast.stmt],
               locks: FrozenSet[str]) -> None:
        for node in stmts:
            if isinstance(node, ast.With):
                self._with(node, locks)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node not in self.skip:
                    # a closure defined here runs later, possibly
                    # without the current locks: scan it lock-free
                    self._block(node.body, frozenset())
            elif isinstance(node, ast.ClassDef):
                continue            # nested classes scanned separately
            else:
                self._stmt_exprs(node, locks)
                for field in _STMT_BLOCKS:
                    sub = getattr(node, field, None)
                    if sub:
                        self._block(sub, locks)
                for h in getattr(node, "handlers", []) or []:
                    self._block(h.body, locks)
                for c in getattr(node, "cases", []) or []:
                    self._block(c.body, locks)

    def _with(self, node: ast.With, locks: FrozenSet[str]) -> None:
        new = locks
        for item in node.items:
            self._expr(item.context_expr, new)
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                line = item.context_expr.lineno
                self.unit.acquisitions.append(Acquisition(
                    lock=lid, line=line, held=new, unit=self.unit.name,
                    waived=self._marked(line, LOCK_ORDER_OK)))
                new = new | {lid}
        self._block(node.body, new)

    def _stmt_exprs(self, stmt: ast.stmt, locks: FrozenSet[str]) -> None:
        for name, value in ast.iter_fields(stmt):
            if name in _STMT_BLOCKS or name in ("handlers", "cases"):
                continue
            vals = value if isinstance(value, list) else [value]
            for v in vals:
                if isinstance(v, ast.expr):
                    self._expr(v, locks)

    # ----------------------------------------------------- expression walk

    def _expr(self, expr: ast.expr, locks: FrozenSet[str]) -> None:
        for node in self._walk(expr):
            if isinstance(node, ast.Attribute) and _is_self(node.value):
                if isinstance(node.ctx, ast.Store):
                    self._access(node.attr, "write", node.lineno, locks)
                elif isinstance(node.ctx, ast.Del):
                    self._access(node.attr, "mutate", node.lineno, locks)
                else:
                    self._access(node.attr, "read", node.lineno, locks)
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                tgt = node.value
                if isinstance(tgt, ast.Attribute) and _is_self(tgt.value):
                    self._access(tgt.attr, "mutate", node.lineno, locks)
                elif (isinstance(tgt, ast.Name) and self.cls is None
                      and tgt.id in self.watch):
                    self._global(tgt.id, node.lineno, locks)
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Store):
                if self.cls is None and node.id in self.watch:
                    self._global(node.id, node.lineno, locks)
            elif isinstance(node, ast.Call):
                self._call(node, locks)

    def _walk(self, expr: ast.expr) -> Iterable[ast.AST]:
        """``ast.walk`` pruned of nested function/lambda bodies that are
        scanned as their own units (thread entries)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if node in self.skip:
                continue
            if isinstance(node, ast.Lambda) and node is not expr:
                # inline lambdas (sort keys etc.) run in-place: fold
                stack.append(node.body)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # ----------------------------------------------------------- recording

    def _access(self, attr: str, kind: str, line: int,
                locks: FrozenSet[str]) -> None:
        if self.cls is None:
            return
        self.unit.accesses.append(Access(
            attr=attr, kind=kind, line=line, locks=locks,
            unit=self.unit.name,
            waived=self._marked(line, THREAD_LOCAL_OK)))

    def _global(self, name: str, line: int, locks: FrozenSet[str]) -> None:
        self.m.global_accesses.append(Access(
            attr=name, kind="mutate", line=line, locks=locks,
            unit=self.unit.name,
            waived=self._marked(line, THREAD_LOCAL_OK)))

    def _call(self, call: ast.Call, locks: FrozenSet[str]) -> None:
        f = call.func
        # in-place mutators: self.X.append(...) / watched_global.update(..)
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            recv = f.value
            if isinstance(recv, ast.Attribute) and _is_self(recv.value):
                self._access(recv.attr, "mutate", call.lineno, locks)
            elif (isinstance(recv, ast.Name) and self.cls is None
                  and recv.id in self.watch):
                self._global(recv.id, call.lineno, locks)
        # intra-module call edges (lock/blocking propagation)
        if (isinstance(f, ast.Attribute) and _is_self(f.value)
                and f.attr in self.class_methods):
            self.unit.calls.append(CallSite(
                callee=("self", f.attr), line=call.lineno, locks=locks,
                unit=self.unit.name,
                waived=self._marked(call.lineno, BLOCKING_OK)))
        elif isinstance(f, ast.Name) and f.id in self.module_funcs:
            self.unit.calls.append(CallSite(
                callee=("mod", f.id), line=call.lineno, locks=locks,
                unit=self.unit.name,
                waived=self._marked(call.lineno, BLOCKING_OK)))
        desc = self._blocking_desc(call)
        if desc is not None:
            self.unit.blockings.append(Blocking(
                desc=desc, line=call.lineno, locks=locks,
                unit=self.unit.name,
                waived=self._marked(call.lineno, BLOCKING_OK)))

    def _blocking_desc(self, call: ast.Call) -> Optional[str]:
        f = call.func
        has_timeout = (_kw(call, "timeout") is not None
                       or _kw(call, "timeout_s") is not None)
        if isinstance(f, ast.Attribute):
            recv = f.value
            if (f.attr == "sleep" and isinstance(recv, ast.Name)
                    and recv.id == "time"):
                return "time.sleep(...)"
            if f.attr == "join" and not call.args and not has_timeout:
                # 1-arg .join is str.join; 0-arg is a thread/process wait
                return ".join() without timeout"
            if f.attr == "wait" and not call.args and not has_timeout:
                return ".wait() without timeout"
            if f.attr == "get" and not call.args and not call.keywords:
                # dict.get always takes a key; bare .get() is a queue
                return ".get() without timeout"
            if (f.attr == "put" and call.args and not has_timeout
                    and isinstance(recv, ast.Attribute)
                    and _is_self(recv.value)):
                blk = _kw(call, "block")
                if not (isinstance(blk, ast.Constant) and blk.value is False):
                    return ".put(...) without timeout"
            if f.attr == "communicate" and not has_timeout:
                return ".communicate() without timeout"
            if (f.attr in SUBPROCESS_WAITS and isinstance(recv, ast.Name)
                    and recv.id == "subprocess" and not has_timeout):
                return f"subprocess.{f.attr}(...) without timeout"
        return None


# ------------------------------------------------------- module scanning


def _spawn_calls(root: ast.AST) -> List[ast.Call]:
    out = []
    for node in ast.walk(root):
        if isinstance(node, ast.Call) and _type_name(node) in (
                "Thread", "Process"):
            out.append(node)
    return out


def _collect_lock_attrs(cls_node: ast.ClassDef, cls: ClassScan) -> None:
    for node in ast.walk(cls_node):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        tname = _type_name(value)
        if tname not in SYNCHRONIZED_TYPES:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Attribute) and _is_self(t.value):
                cls.sync_attrs.add(t.attr)
                if tname in LOCK_TYPES:
                    cls.lock_attrs.add(t.attr)
                if tname == "RLock":
                    cls.rlock_attrs.add(t.attr)


def _thread_shared_decl(cls_node: ast.ClassDef
                        ) -> Tuple[Optional[Tuple[str, ...]], int]:
    for node in cls_node.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_THREAD_SHARED"):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                names = tuple(e.value for e in node.value.elts
                              if isinstance(e, ast.Constant)
                              and isinstance(e.value, str))
                return names, node.lineno
            return (), node.lineno
    return None, 0


def _base_names(cls_node: ast.ClassDef) -> Tuple[str, ...]:
    out = []
    for b in cls_node.bases:
        if isinstance(b, ast.Attribute):
            out.append(b.attr)
        elif isinstance(b, ast.Name):
            out.append(b.id)
    return tuple(out)


def _scan_class(mscan: ModuleScan, cls_node: ast.ClassDef, qual: str,
                module_funcs: Set[str]) -> ClassScan:
    cls = ClassScan(name=qual, line=cls_node.lineno,
                    bases=_base_names(cls_node))
    _collect_lock_attrs(cls_node, cls)
    cls.thread_shared, cls.thread_shared_line = _thread_shared_decl(cls_node)
    methods = [n for n in cls_node.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    method_names = {m.name for m in methods}

    for meth in methods:
        nested = {n.name: n for n in ast.walk(meth)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not meth}
        # -------- discover spawns first: they define the unit split
        entry_nodes: Dict[ast.AST, str] = {}   # nested node -> unit name
        for call in _spawn_calls(meth):
            tname = _type_name(call)
            target = _kw(call, "target")
            kind = "process" if tname == "Process" else "thread"
            if target is None:
                continue
            if isinstance(target, ast.Attribute) and _is_self(target.value):
                ident = f"{qual}.{target.attr}"
                entry_unit = (target.attr
                              if target.attr in method_names else None)
            elif (isinstance(target, ast.Name) and target.id in nested):
                ident = f"{qual}.{meth.name}:{target.id}"
                entry_unit = f"{meth.name}:{target.id}"
                if kind == "thread":
                    entry_nodes[nested[target.id]] = entry_unit
            elif isinstance(target, ast.Lambda):
                ident = f"{qual}.{meth.name}:<lambda>"
                entry_unit = f"{meth.name}:<lambda>"
                if kind == "thread":
                    entry_nodes[target] = entry_unit
            elif isinstance(target, ast.Attribute):
                ident = f"{qual}.{meth.name}:{target.attr}"
                entry_unit = None
            elif isinstance(target, ast.Name):
                ident = f"{qual}.{meth.name}:{target.id}"
                entry_unit = None
            else:
                ident = f"{qual}.{meth.name}:<target>"
                entry_unit = None
            if kind == "process":
                ident = f"process:{ident}"
                entry_unit = None     # separate address space
            spawn = Spawn(ident=ident, line=call.lineno, kind=kind,
                          entry_unit=entry_unit)
            cls.spawns.append(spawn)
            mscan.spawns.append(spawn)
            if entry_unit is not None and kind == "thread":
                cls.entries[entry_unit] = ident
        # nested defs reachable from a nested thread entry run on that
        # thread too (data.py's producer -> put_until_stopped chain)
        reached: Dict[ast.AST, str] = dict(entry_nodes)
        frontier = list(entry_nodes)
        while frontier:
            node = frontier.pop()
            unit_name = reached[node]
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id in nested):
                    cand = nested[sub.func.id]
                    if cand not in reached:
                        reached[cand] = unit_name
                        frontier.append(cand)

        # -------- scan the method body (minus on-thread closures)
        unit_key = (meth.name if meth.name not in cls.units
                    else f"{meth.name}@{meth.lineno}")
        unit = UnitScan(name=unit_key)
        sc = _Scanner(mscan, cls, unit, module_funcs, method_names,
                      skip_nodes=set(reached), watch_globals=set())
        sc.scan_function(meth)
        cls.units[unit_key] = unit
        cls.by_name.setdefault(meth.name, []).append(unit_key)

        # -------- scan each on-thread closure as its own unit
        by_unit: Dict[str, List[ast.AST]] = collections.defaultdict(list)
        for node, unit_name in reached.items():
            by_unit[unit_name].append(node)
        for unit_name, nodes in by_unit.items():
            tunit = UnitScan(name=unit_name)
            tsc = _Scanner(mscan, cls, tunit, module_funcs, method_names,
                           skip_nodes=set(), watch_globals=set())
            tsc.scan_bodies(nodes)
            cls.units[unit_name] = tunit

    # Thread subclass: run() is an entry on the spawned thread
    if "Thread" in cls.bases and "run" in method_names:
        cls.entries.setdefault("run", f"{qual}.run")
        mscan.spawns.append(Spawn(ident=f"{qual}.run", line=cls.line,
                                  kind="thread", entry_unit="run"))
    # HTTP request handlers: do_* methods run on server threads
    if any("RequestHandler" in b for b in cls.bases):
        for m in sorted(method_names):
            if m.startswith("do_"):
                ident = f"handler:{qual}.{m}"
                cls.entries.setdefault(m, ident)
                mscan.spawns.append(Spawn(ident=ident, line=cls.line,
                                          kind="handler", entry_unit=m))
    return cls


def scan_module(src: str, path: str,
                watch_globals: Sequence[str] = ()) -> ModuleScan:
    """Parse one module into its concurrency skeleton (no findings yet:
    :func:`audit_modules` turns scans + contracts into findings)."""
    tree = ast.parse(src)
    mscan = ModuleScan(path=path, lines=src.splitlines())

    module_funcs = {n.name for n in tree.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if (isinstance(value, ast.Call)
                    and _type_name(value) in LOCK_TYPES):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        mscan.module_locks.add(t.id)

    def walk_scope(body: Sequence[ast.stmt], qual: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                q = f"{qual}.{node.name}" if qual else node.name
                mscan.classes.append(
                    _scan_class(mscan, node, q, module_funcs))
                walk_scope(node.body, q)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_scope(node.body, f"{qual}.{node.name}"
                           if qual else node.name)

    walk_scope(tree.body, "")

    watch = set(watch_globals)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            unit = UnitScan(name=node.name)
            sc = _Scanner(mscan, None, unit, module_funcs, set(),
                          skip_nodes=set(), watch_globals=watch)
            sc.scan_function(node)
            mscan.funcs[node.name] = unit
            # module-level spawn sites (mplane's exporter thread)
            for call in _spawn_calls(node):
                tname = _type_name(call)
                target = _kw(call, "target")
                if target is None or _enclosed_in_class(call, mscan):
                    continue
                if isinstance(target, ast.Attribute):
                    tid = target.attr
                elif isinstance(target, ast.Name):
                    tid = target.id
                elif isinstance(target, ast.Lambda):
                    tid = "<lambda>"
                else:
                    tid = "<target>"
                ident = f"{node.name}:{tid}"
                if tname == "Process":
                    ident = f"process:{ident}"
                mscan.spawns.append(Spawn(
                    ident=ident, line=call.lineno,
                    kind="process" if tname == "Process" else "thread",
                    entry_unit=None))
    return mscan


def _enclosed_in_class(call: ast.Call, mscan: ModuleScan) -> bool:
    """True when a spawn call line was already claimed by a class scan
    (a method inside a class inside a module function is rare; class
    scans record their spawns themselves)."""
    return any(s.line == call.lineno for c in mscan.classes
               for s in c.spawns)


# ------------------------------------------------------ contracts + audit


@dataclasses.dataclass(frozen=True)
class ConcurrencyContract:
    """Declarative per-module concurrency contract (additive, like
    ``PassBudget``/``PlanContract``).

    ``threads`` is the canonical inventory of the module's threads of
    control (spawn sites, handlers, process entries) — drift in either
    direction is a finding, so a new thread cannot land silently.
    ``external_roots`` names class methods driven from threads the
    module itself never spawns (``{"ServingRuntime": {"submit":
    "realtime-driver", ...}}``). ``shared_globals`` are module-level
    names shared across threads whose mutations must hold a
    module-level lock."""

    module: str
    threads: Tuple[str, ...] = ()
    external_roots: Mapping[str, Mapping[str, str]] = dataclasses.field(
        default_factory=dict)
    shared_globals: Tuple[str, ...] = ()
    reason: str = ""


def _roots_by_unit(cls: ClassScan,
                   external: Mapping[str, str]) -> Dict[str, FrozenSet[str]]:
    """Assign every unit its set of threads of control.

    Thread entries seed their own root. Public methods (and private
    methods never called intra-class — their callers are outside) seed
    the ``caller`` root. ``external_roots`` add declared cross-thread
    drivers. Roots then propagate along intra-class call edges to a
    fixpoint, so a helper only called from the monitor loop carries
    exactly the monitor root. ``__init__`` (and other dunders) seed
    nothing: construction precedes concurrency."""
    roots: Dict[str, Set[str]] = {u: set() for u in cls.units}
    indeg: Dict[str, int] = {u: 0 for u in cls.units}
    edges: List[Tuple[str, str]] = []
    for u, unit in cls.units.items():
        for call in unit.calls:
            if call.callee[0] != "self":
                continue
            for v in cls.by_name.get(call.callee[1], []):
                edges.append((u, v))
                indeg[v] += 1
    for u, ident in cls.entries.items():
        if u in roots:
            roots[u].add(ident)
    for u in cls.units:
        meth = u.split("@")[0]
        if ":" in u or u in cls.entries:
            continue
        if meth.startswith("__") and meth != "__call__":
            continue
        if not meth.startswith("_") or indeg[u] == 0:
            roots[u].add("caller")
    for meth, root in external.items():
        for u in cls.by_name.get(meth, []):
            roots[u].add(root)
    changed = True
    while changed:
        changed = False
        for u, v in edges:
            if not roots[u] <= roots[v]:
                roots[v] |= roots[u]
                changed = True
    return {u: frozenset(r) for u, r in roots.items()}


def _shared_attr_findings(mscan: ModuleScan, cls: ClassScan,
                          roots: Dict[str, FrozenSet[str]]
                          ) -> List[ConcFinding]:
    by_attr: Dict[str, List[Access]] = collections.defaultdict(list)
    for unit in cls.units.values():
        for a in unit.accesses:
            by_attr[a.attr].append(a)
    declared = set(cls.thread_shared or ())
    out: List[ConcFinding] = []
    for attr, accesses in sorted(by_attr.items()):
        if attr in cls.sync_attrs:
            continue
        attr_roots = set()
        for a in accesses:
            attr_roots |= roots.get(a.unit, frozenset())
        muts = [a for a in accesses if a.kind in ("write", "mutate")
                and a.unit.split("@")[0] != "__init__"]
        if not muts:
            continue
        if len(attr_roots) < 2 and attr not in declared:
            continue
        guards = sorted({lk for a in accesses for lk in a.locks})
        for a in muts:
            if a.locks or a.waived:
                continue
            rtxt = ", ".join(sorted(attr_roots)) or "caller"
            hint = (f"; other sites guard it with {', '.join(guards)}"
                    if guards else "")
            out.append(ConcFinding(
                "unguarded-shared", mscan.path, a.line,
                f"{cls.name}.{attr} mutated without a lock but shared "
                f"across threads of control [{rtxt}]{hint}; guard the "
                f"mutation or annotate '# {THREAD_LOCAL_OK} <reason>'"))
    # declared-but-nonexistent attrs keep _THREAD_SHARED honest
    for attr in sorted(declared - set(by_attr)):
        out.append(ConcFinding(
            "contract-drift", mscan.path, cls.thread_shared_line,
            f"{cls.name}._THREAD_SHARED declares '{attr}' but no such "
            f"attribute is accessed in the class"))
    return out


def _blocking_findings(mscan: ModuleScan) -> List[ConcFinding]:
    """Direct blocking-while-locked sites plus calls that bubble a
    blocking callee up under a held lock (intra-module resolution)."""
    units: Dict[Tuple[str, str], UnitScan] = {}
    name_map: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for cls in mscan.classes:
        for u, unit in cls.units.items():
            units[(cls.name, u)] = unit
        for meth, unit_names in cls.by_name.items():
            name_map[(cls.name, meth)] = [(cls.name, u) for u in unit_names]
    for fname, unit in mscan.funcs.items():
        units[("", fname)] = unit
        name_map[("", fname)] = [("", fname)]

    def callees(key: Tuple[str, str], call: CallSite
                ) -> List[Tuple[str, str]]:
        if call.callee[0] == "self":
            return name_map.get((key[0], call.callee[1]), [])
        return name_map.get(("", call.callee[1]), [])

    may_block: Dict[Tuple[str, str], Set[str]] = {
        k: {b.desc for b in u.blockings if not b.waived}
        for k, u in units.items()}
    changed = True
    while changed:
        changed = False
        for k, u in units.items():
            for call in u.calls:
                for c in callees(k, call):
                    extra = may_block.get(c, set()) - may_block[k]
                    if extra:
                        may_block[k] |= extra
                        changed = True

    out: List[ConcFinding] = []
    for k, u in units.items():
        for b in u.blockings:
            if b.locks and not b.waived:
                out.append(ConcFinding(
                    "blocking-under-lock", mscan.path, b.line,
                    f"{b.desc} while holding {', '.join(sorted(b.locks))}"
                    f" — a blocked lock holder stalls every other thread"
                    f" of control; move the wait outside the lock or "
                    f"annotate '# {BLOCKING_OK} <reason>'"))
        for call in u.calls:
            if not call.locks or call.waived:
                continue
            bubbled = set()
            for c in callees(k, call):
                bubbled |= may_block.get(c, set())
            if bubbled:
                out.append(ConcFinding(
                    "blocking-under-lock", mscan.path, call.line,
                    f"call under {', '.join(sorted(call.locks))} reaches "
                    f"a blocking operation ({', '.join(sorted(bubbled))})"
                    f"; move the call outside the lock or annotate "
                    f"'# {BLOCKING_OK} <reason>'"))
    return out


def _lock_edges(mscan: ModuleScan
                ) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Directed held->acquired edges, with acquisitions propagated
    through intra-module calls (a callee's acquisitions happen while
    the caller's locks are held). Waived acquisitions contribute no
    edges and do not propagate."""
    units: Dict[Tuple[str, str], UnitScan] = {}
    name_map: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for cls in mscan.classes:
        for u, unit in cls.units.items():
            units[(cls.name, u)] = unit
        for meth, unit_names in cls.by_name.items():
            name_map[(cls.name, meth)] = [(cls.name, u) for u in unit_names]
    for fname, unit in mscan.funcs.items():
        units[("", fname)] = unit
        name_map[("", fname)] = [("", fname)]

    may_acquire: Dict[Tuple[str, str], Set[str]] = {
        k: {a.lock for a in u.acquisitions if not a.waived}
        for k, u in units.items()}
    changed = True
    while changed:
        changed = False
        for k, u in units.items():
            for call in u.calls:
                keys = (name_map.get((k[0], call.callee[1]), [])
                        if call.callee[0] == "self"
                        else name_map.get(("", call.callee[1]), []))
                for c in keys:
                    extra = may_acquire.get(c, set()) - may_acquire[k]
                    if extra:
                        may_acquire[k] |= extra
                        changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for k, u in units.items():
        for a in u.acquisitions:
            if a.waived:
                continue
            for h in a.held:
                edges.setdefault((h, a.lock), (mscan.path, a.line))
        for call in u.calls:
            if not call.locks:
                continue
            keys = (name_map.get((k[0], call.callee[1]), [])
                    if call.callee[0] == "self"
                    else name_map.get(("", call.callee[1]), []))
            for c in keys:
                for acq in may_acquire.get(c, set()):
                    for h in call.locks:
                        edges.setdefault((h, acq),
                                         (mscan.path, call.line))
    return edges


def _cycles(edges: Dict[Tuple[str, str], Tuple[str, int]],
            reentrant: FrozenSet[str] = frozenset()) -> List[List[str]]:
    graph: Dict[str, Set[str]] = collections.defaultdict(set)
    for a, b in edges:
        if a == b and a in reentrant:
            continue        # RLock re-acquisition, not a deadlock
        graph[a].add(b)
    # Tarjan SCC; report components of size > 1 and self-loops
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(set(graph) | {b for bs in graph.values() for b in bs}):
        if v not in index:
            strongconnect(v)
    out = []
    for comp in sccs:
        if len(comp) > 1:
            out.append(sorted(comp))
        elif comp[0] in graph.get(comp[0], set()):
            out.append(comp)
    return out


def _contract_findings(mscan: ModuleScan,
                       contract: Optional[ConcurrencyContract]
                       ) -> List[ConcFinding]:
    out: List[ConcFinding] = []
    discovered = {s.ident for s in mscan.spawns}
    if contract is None:
        if discovered:
            first = min(mscan.spawns, key=lambda s: s.line)
            out.append(ConcFinding(
                "contract-drift", mscan.path, first.line,
                f"module spawns threads of control ({', '.join(sorted(discovered))}) "
                f"but declares no ConcurrencyContract — add one to "
                f"analysis.concurrency_audit.REFERENCE_CONTRACTS"))
        return out
    declared = set(contract.threads)
    for ident in sorted(discovered - declared):
        line = min(s.line for s in mscan.spawns if s.ident == ident)
        out.append(ConcFinding(
            "contract-drift", mscan.path, line,
            f"undeclared thread of control '{ident}' — add it to the "
            f"module's ConcurrencyContract.threads"))
    for ident in sorted(declared - discovered):
        out.append(ConcFinding(
            "contract-drift", mscan.path, 1,
            f"ConcurrencyContract declares thread '{ident}' but no such "
            f"spawn site exists (stale contract)"))
    class_names = {c.name for c in mscan.classes}
    for cname, meths in contract.external_roots.items():
        cls = next((c for c in mscan.classes if c.name == cname), None)
        if cls is None:
            out.append(ConcFinding(
                "contract-drift", mscan.path, 1,
                f"ConcurrencyContract names external roots for missing "
                f"class '{cname}' (have: {', '.join(sorted(class_names))})"))
            continue
        for meth in meths:
            if meth not in cls.by_name:
                out.append(ConcFinding(
                    "contract-drift", mscan.path, cls.line,
                    f"ConcurrencyContract names external root for "
                    f"missing method '{cname}.{meth}'"))
    watched = set(contract.shared_globals)
    seen = set()
    for a in mscan.global_accesses:
        if a.attr not in watched:
            continue
        seen.add(a.attr)
        if not a.locks and not a.waived:
            out.append(ConcFinding(
                "global-unguarded", mscan.path, a.line,
                f"shared module global '{a.attr}' mutated outside any "
                f"module-level lock; guard it or annotate "
                f"'# {THREAD_LOCAL_OK} <reason>'"))
    for name in sorted(watched - seen):
        # declared but never mutated in module functions: fine (may be
        # read-only or mutated only at import time) — not a finding
        pass
    return out


@dataclasses.dataclass
class AuditReport:
    """Aggregated Half-1 result over a set of modules."""

    findings: List[ConcFinding]
    inventory: Dict[str, List[str]]          # module -> thread idents
    lock_edges: Dict[Tuple[str, str], Tuple[str, int]]
    cycles: List[List[str]]
    modules: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "modules": self.modules,
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "inventory": self.inventory,
            "lock_edges": [
                {"from": a, "to": b, "path": p, "line": ln}
                for (a, b), (p, ln) in sorted(self.lock_edges.items())],
            "cycles": self.cycles,
        }


def audit_modules(scans: Sequence[ModuleScan],
                  contracts: Mapping[str, ConcurrencyContract]
                  ) -> AuditReport:
    """Run every Half-1 check over pre-parsed module scans."""
    findings: List[ConcFinding] = []
    inventory: Dict[str, List[str]] = {}
    all_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    reentrant: Set[str] = set()
    for mscan in scans:
        for cls in mscan.classes:
            reentrant.update(f"{cls.name}.{a}" for a in cls.rlock_attrs)
        contract = contracts.get(mscan.path)
        ext = contract.external_roots if contract else {}
        for cls in mscan.classes:
            roots = _roots_by_unit(cls, ext.get(cls.name, {}))
            findings.extend(_shared_attr_findings(mscan, cls, roots))
        findings.extend(_blocking_findings(mscan))
        findings.extend(_contract_findings(mscan, contract))
        for edge, site in _lock_edges(mscan).items():
            all_edges.setdefault(edge, site)
        if mscan.spawns:
            inventory[mscan.path] = sorted({s.ident for s in mscan.spawns})
    cycles = _cycles(all_edges, frozenset(reentrant))
    for comp in cycles:
        path, line = min(
            (all_edges[(a, b)] for (a, b) in all_edges
             if a in comp and b in comp), default=("<graph>", 0))
        findings.append(ConcFinding(
            "lock-order-cycle", path, line,
            f"lock-acquisition-order cycle: {' -> '.join(comp + comp[:1])}"
            f" — a consistent global order (or an id-ordered acquisition "
            f"with '# {LOCK_ORDER_OK} <reason>') is required"))
    findings.sort(key=lambda f: (f.path, f.line, f.kind))
    return AuditReport(findings=findings, inventory=inventory,
                       lock_edges=all_edges, cycles=cycles,
                       modules=len(scans))


def audit_source(src: str, path: str,
                 contract: Optional[ConcurrencyContract] = None
                 ) -> AuditReport:
    """Audit one in-memory module (unit tests + the seeded drills)."""
    contracts = {path: contract} if contract else {}
    watch = contract.shared_globals if contract else ()
    return audit_modules([scan_module(src, path, watch)], contracts)


def package_root() -> str:
    """Absolute path of the installed ``distributed_embeddings_tpu``
    package directory (the scan root)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def audit_repo(root: Optional[str] = None,
               contracts: Optional[Mapping[str, ConcurrencyContract]] = None
               ) -> AuditReport:
    """Scan every package module and audit it against
    :data:`REFERENCE_CONTRACTS` (module paths are package-relative,
    ``parallel/serving.py`` style)."""
    root = package_root() if root is None else root
    contracts = REFERENCE_CONTRACTS if contracts is None else contracts
    scans = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            with open(full, "r", encoding="utf-8") as f:
                src = f.read()
            contract = contracts.get(rel)
            watch = contract.shared_globals if contract else ()
            scans.append(scan_module(src, rel, watch))
    return audit_modules(scans, contracts)


# ------------------------------------------------- the reference contracts

#: The serving plane's thread-shared-state contracts. Additive: a new
#: concurrent module (or a new thread in a contracted one) fails the
#: gate until its contract names the thread of control.
REFERENCE_CONTRACTS: Dict[str, ConcurrencyContract] = {
    c.module: c for c in (
        ConcurrencyContract(
            module="parallel/supervisor.py",
            threads=(
                "Supervisor._monitor_loop",
                "Supervisor._send_loop",
                "Supervisor._spawn_worker:<lambda>",
                "process:Supervisor._spawn_worker:_worker_main",
            ),
            reason="monitor owns the socket + crash path; sender drains "
                   "the send queue; the accept lambda bounds worker "
                   "connect; the worker is a spawn-context process "
                   "(own address space — excluded from shared state)"),
        ConcurrencyContract(
            module="parallel/serving.py",
            threads=("RealtimeDriver._run",),
            external_roots={
                # In OnlineRuntime realtime mode ONE ServingRuntime is
                # driven from two threads the module never spawns: the
                # RealtimeDriver submits/polls while the trainer thread
                # publishes snapshots; the mplane exporter thread may
                # scrape _collect mid-load (check_obsplane drill).
                "ServingRuntime": {
                    "submit": "realtime-driver",
                    "poll": "realtime-driver",
                    "flush": "realtime-driver",
                    "install_snapshot": "trainer",
                    "note_train_step": "trainer",
                    "_collect": "metrics-exporter",
                },
            },
            reason="open-loop realtime arrivals vs trainer-side RCU "
                   "snapshot publication on one runtime instance; the "
                   "trace ring (self.traces, a TraceBuffer) is written "
                   "from submit/poll/flush threads and read by the "
                   "exporter's _collect + stats() — synchronized "
                   "internally (SYNCHRONIZED_TYPES)"),
        ConcurrencyContract(
            module="utils/reqtrace.py",
            threads=(),
            external_roots={
                # the buffer spawns nothing but is driven from every
                # serving-plane thread: the driver finishes traces, the
                # supervisor's monitor thread finishes + appends
                # restart marks, the mplane exporter thread reads
                # stats() for the trace-ring gauge, and online.py's
                # trainer thread drains into the flight recorder
                "TraceBuffer": {
                    "begin": "realtime-driver",
                    "finish": "supervisor-monitor",
                    "append_event": "supervisor-monitor",
                    "annotate": "supervisor-monitor",
                    "stats": "metrics-exporter",
                    "drain_new": "trainer",
                },
            },
            reason="one internal lock serializes the active table, the "
                   "bounded retained ring, and the drain cursor; every "
                   "public method is a single lock-held critical "
                   "section, so cross-thread callers need no external "
                   "ordering"),
        ConcurrencyContract(
            module="utils/obs.py",
            threads=(),
            shared_globals=(
                "_counters", "_events", "_event_taps",
                "_server_started", "_compile_listener_installed",
            ),
            reason="module-level counters/events are written from "
                   "serving, supervisor and exporter threads; every "
                   "mutation holds the module lock"),
        ConcurrencyContract(
            module="utils/mplane.py",
            threads=(
                "start_http_exporter:serve_forever",
                "handler:start_http_exporter.Handler.do_GET",
            ),
            reason="the scrape endpoint renders the registry from "
                   "server threads while hot paths observe into "
                   "sketches; lock hierarchy registry -> family -> "
                   "sketch, sketch merge id-ordered"),
        ConcurrencyContract(
            module="utils/data.py",
            threads=("RawBinaryDataset._iter_range:producer",),
            reason="one bounded-queue prefetch producer per iteration; "
                   "it touches only closure state + the synchronized "
                   "queue/stop-event pair"),
    )
}


# ====================================================================
# Half 2 — explicit-state interleaving model checker
# ====================================================================


@dataclasses.dataclass(frozen=True)
class Model:
    """An explicit-state transition system: hashable states, string
    action labels, deterministic ``step``, named invariants checked on
    every reachable state."""

    name: str
    initial: Any
    enabled: Callable[[Any], Tuple[str, ...]]
    step: Callable[[Any, str], Any]
    invariants: Mapping[str, Callable[[Any], bool]]


@dataclasses.dataclass(frozen=True)
class ProofResult:
    """Outcome of one exhaustive exploration."""

    model: str
    ok: bool
    states: int
    transitions: int
    violated: Optional[str] = None
    trace: Tuple[str, ...] = ()

    def __str__(self) -> str:
        if self.ok:
            return (f"{self.model}: PROVED over {self.states} states / "
                    f"{self.transitions} transitions")
        return (f"{self.model}: VIOLATED '{self.violated}' after "
                f"{len(self.trace)} steps: {' -> '.join(self.trace)}")


def explore(model: Model, max_states: int = 500_000) -> ProofResult:
    """Exhaustive BFS over every interleaving of ``model``.

    Checks every invariant on every reachable state; on the first
    violation, reconstructs the shortest action trace (the
    counterexample a refuted mutant prints). Raises ``RuntimeError``
    past ``max_states`` — an unbounded model is an authoring bug, not
    a proof."""
    parent: Dict[Any, Optional[Tuple[Any, str]]] = {model.initial: None}
    frontier = collections.deque([model.initial])
    transitions = 0

    def violation(state: Any) -> Optional[str]:
        for name, inv in model.invariants.items():
            if not inv(state):
                return name
        return None

    def trace_to(state: Any) -> Tuple[str, ...]:
        acts: List[str] = []
        cur = state
        while parent[cur] is not None:
            prev, act = parent[cur]
            acts.append(act)
            cur = prev
        return tuple(reversed(acts))

    bad = violation(model.initial)
    if bad is not None:
        return ProofResult(model.name, False, 1, 0, bad, ())
    while frontier:
        state = frontier.popleft()
        for action in model.enabled(state):
            nxt = model.step(state, action)
            transitions += 1
            if nxt in parent:
                continue
            parent[nxt] = (state, action)
            bad = violation(nxt)
            if bad is not None:
                return ProofResult(model.name, False, len(parent),
                                   transitions, bad, trace_to(nxt))
            if len(parent) > max_states:
                raise RuntimeError(
                    f"model '{model.name}' exceeded {max_states} states "
                    f"— not a bounded model")
            frontier.append(nxt)
    return ProofResult(model.name, True, len(parent), transitions)


def prove(model: Model, max_states: int = 500_000) -> ProofResult:
    """Explore and require every invariant to hold."""
    return explore(model, max_states)


def refute(model: Model, max_states: int = 500_000) -> ProofResult:
    """Explore a seeded mutant and require a counterexample (the
    drill: a checker that cannot refute a broken protocol proves
    nothing)."""
    return explore(model, max_states)


# ----------------------------------------------------------- seqlock model

# Reader program counters
_R_IDLE, _R_HDR, _R_COPY, _R_VERIFY = 0, 1, 2, 3


def seqlock_model(mutant: Optional[str] = None, *, publishes: int = 3,
                  words: int = 2, retries: int = 2,
                  reads: int = 2) -> Model:
    """The ``utils/shm.py`` seqlock at word granularity.

    Writer per publish ``s`` (buffer ``s % 2``): one atomic header pack
    (``begin=s, end=0, crc=crc(payload_s)`` — one struct.pack slice
    write in the real code), then ``words`` separate payload-word
    writes, then the ``end=s`` stamp, then the ``latest`` flip. Reader
    per attempt: snapshot ``latest``, read the header atomically,
    require ``begin == end != 0``, copy the payload word by word, then
    verify the CRC over the *copied* words against the copied header
    (a mixed copy hashes to a distinct value — crc32's job here).
    ``publishes >= 3`` makes lapping reachable: seqs 1 and 3 share
    buffer 1, so a reader holding seq-1's header can race seq-3's
    overwrite mid-copy.

    Mutants: ``no_crc`` skips the verify (a lapped torn copy is then
    accepted — violates ``no-torn-accept``); ``stamps_swapped`` writes
    the end-stamp up-front with the header (the buffer claims
    completeness over stale words — violates ``stamp-honesty``)."""
    if mutant not in (None, "no_crc", "stamps_swapped"):
        raise ValueError(f"unknown seqlock mutant: {mutant}")
    W = int(words)
    empty_buf = (0, 0, 0, (0,) * W)

    # state: (w_seq, w_pc, bufs, latest, r_pc, r_hdr, r_copied,
    #         r_attempt, r_done, last_accept)
    # w_pc: 0 = header next; 1..W = word w_pc-1 next; W+1 = stamp next;
    #       W+2 = flip next
    initial = (1, 0, (empty_buf, empty_buf), 0,
               _R_IDLE, None, (), 0, 0, None)

    def enabled(s: Any) -> Tuple[str, ...]:
        (w_seq, w_pc, bufs, latest, r_pc, r_hdr, r_copied,
         r_attempt, r_done, last_accept) = s
        acts: List[str] = []
        if w_seq <= publishes:
            acts.append("writer")
        if r_done < reads:
            acts.append("reader")
        return tuple(acts)

    def step(s: Any, action: str) -> Any:
        (w_seq, w_pc, bufs, latest, r_pc, r_hdr, r_copied,
         r_attempt, r_done, last_accept) = s
        bufs = list(bufs)
        if action == "writer":
            b = w_seq % 2
            begin, end, crc, wrds = bufs[b]
            if w_pc == 0:                       # atomic header pack
                end0 = w_seq if mutant == "stamps_swapped" else 0
                bufs[b] = (w_seq, end0, w_seq, wrds)
                w_pc = 1
            elif w_pc <= W:                     # payload word w_pc-1
                wl = list(wrds)
                wl[w_pc - 1] = w_seq
                bufs[b] = (begin, end, crc, tuple(wl))
                w_pc += 1
            elif w_pc == W + 1:                 # end-stamp
                bufs[b] = (begin, w_seq, crc, wrds)
                w_pc += 1
            else:                               # latest flip
                latest = w_seq
                w_seq += 1
                w_pc = 0
        else:
            def give_up_or_retry():
                # bounded retries, then None (keep previous snapshot)
                if r_attempt + 1 >= retries:
                    return _R_IDLE, None, (), 0, r_done + 1
                return _R_IDLE, None, (), r_attempt + 1, r_done
            if r_pc == _R_IDLE:
                if latest == 0:                 # nothing published yet
                    r_done += 1
                else:
                    r_hdr = bufs[latest % 2][:3]    # atomic header read
                    r_pc = _R_HDR
            elif r_pc == _R_HDR:
                begin, end, crc = r_hdr
                if begin == end and begin != 0:
                    r_pc, r_copied = _R_COPY, ()
                else:
                    r_pc, r_hdr, r_copied, r_attempt, r_done = \
                        give_up_or_retry()
            elif r_pc == _R_COPY:
                b = r_hdr[0] % 2
                r_copied = r_copied + (bufs[b][3][len(r_copied)],)
                if len(r_copied) == W:
                    r_pc = _R_VERIFY
            else:                               # _R_VERIFY
                begin, end, crc = r_hdr
                uniform = len(set(r_copied)) == 1
                computed = r_copied[0] if uniform else -1
                ok = (computed == crc) or mutant == "no_crc"
                if ok:
                    last_accept = (begin, crc, r_copied)
                    r_pc, r_hdr, r_copied, r_attempt = _R_IDLE, None, (), 0
                    r_done += 1
                else:
                    r_pc, r_hdr, r_copied, r_attempt, r_done = \
                        give_up_or_retry()
        return (w_seq, w_pc, tuple(bufs), latest, r_pc, r_hdr,
                r_copied, r_attempt, r_done, last_accept)

    def no_torn_accept(s: Any) -> bool:
        last_accept = s[9]
        if last_accept is None:
            return True
        begin, crc, copied = last_accept
        return (len(set(copied)) == 1 and copied[0] == begin
                and 1 <= begin <= publishes)

    def stamp_honesty(s: Any) -> bool:
        for begin, end, crc, wrds in s[2]:
            if begin == end and begin != 0:
                if any(w != begin for w in wrds) or crc != begin:
                    return False
        return True

    def writer_never_blocks(s: Any) -> bool:
        return s[0] > publishes or "writer" in enabled(s)

    def bounded_retries(s: Any) -> bool:
        return s[7] < retries

    return Model(
        name=f"seqlock[{mutant or 'faithful'}]",
        initial=initial, enabled=enabled, step=step,
        invariants={
            "no-torn-accept": no_torn_accept,
            "stamp-honesty": stamp_honesty,
            "writer-never-blocks": writer_never_blocks,
            "bounded-retries": bounded_retries,
        })


# -------------------------------------------------------- supervisor model

_ALIVE, _HUNG, _DEAD, _RESTARTING, _EXHAUSTED = 0, 1, 2, 3, 4


def supervisor_model(mutant: Optional[str] = None, *, ticks: int = 8,
                     submits: int = 2, publishes: int = 2,
                     faults: int = 2, restarts: int = 2,
                     deadline: int = 2, backoff: int = 1) -> Model:
    """The ``parallel/supervisor.py`` heartbeat state machine, round-
    based on a virtual clock (``tick`` advances time then runs the
    monitor's checks — exactly the real monitor loop's shape).

    An ALIVE worker pongs unconditionally on every monitor pass (the
    worker's pong loop has no slow path — *failing* to pong IS the
    hang, which is why the deadline is a meaningful detector).

    Worker actions: serve (answers the
    lowest in-flight rid), crash (socket EOF — detected on the next
    monitor pass), hang (alive process, frozen pongs — detected only by
    the deadline). Caller actions: submit (rid assignment; during an
    outage the crash path answers a typed Unavailable immediately),
    publish (the seqlock write — enabled in EVERY state by
    construction, which the ``publish-never-blocks`` invariant makes
    explicit), ingest (an alive worker reads the latest snapshot). The
    monitor detects a crash on its next pass and a hang once
    ``now - last_pong > deadline``, answers every stranded rid, then
    restarts under the budget; a reborn worker re-ingests the latest
    published snapshot before serving.

    Mutant ``deadline_off_by_one`` declares the hang one tick late
    (``> deadline + 1``) — violates ``hang-detected-within-deadline``:
    a state exists where the worker has been silent longer than the
    contract allows yet is still undetected."""
    if mutant not in (None, "deadline_off_by_one"):
        raise ValueError(f"unknown supervisor mutant: {mutant}")
    limit = deadline + (1 if mutant == "deadline_off_by_one" else 0)

    # state: (now, status, last_pong, restart_at, n_restarts, next_rid,
    #         lo, n_served, n_unavail, published, ingested, subs_left,
    #         faults_left)
    initial = (0, _ALIVE, 0, 0, 0, 0, 0, 0, 0, 0, 0, submits, faults)

    def enabled(s: Any) -> Tuple[str, ...]:
        (now, status, last_pong, restart_at, n_restarts, next_rid, lo,
         n_served, n_unavail, published, ingested, subs_left,
         faults_left) = s
        acts: List[str] = []
        if subs_left > 0:
            acts.append("submit")
        if published < publishes:
            acts.append("publish")      # NEVER gated on worker status
        if status == _ALIVE:
            if lo < next_rid:
                acts.append("serve")
            if ingested < published:
                acts.append("ingest")
            if faults_left > 0:
                acts.append("crash")
                acts.append("hang")
        if now < ticks:
            acts.append("tick")
        return tuple(acts)

    def step(s: Any, action: str) -> Any:
        (now, status, last_pong, restart_at, n_restarts, next_rid, lo,
         n_served, n_unavail, published, ingested, subs_left,
         faults_left) = s
        if action == "submit":
            subs_left -= 1
            next_rid += 1
            if status in (_RESTARTING, _EXHAUSTED):
                # DETECTED outage: the crash path answers a typed
                # Unavailable immediately, rid assignment stays
                # monotone. (An *undetected* crash/hang leaves the rid
                # in flight; the monitor's detection pass answers it.)
                lo = next_rid
                n_unavail += 1
        elif action == "publish":
            published += 1
        elif action == "ingest":
            ingested = published
        elif action == "serve":
            lo += 1
            n_served += 1
        elif action == "crash":
            status = _DEAD
            faults_left -= 1
        elif action == "hang":
            status = _HUNG
            faults_left -= 1
        else:                           # tick: clock, then monitor pass
            now += 1
            detected = (status == _DEAD
                        or (status == _HUNG and now - last_pong > limit))
            if detected:
                n_unavail += next_rid - lo      # answer every stranded rid
                lo = next_rid
                if n_restarts >= restarts:
                    status = _EXHAUSTED
                else:
                    n_restarts += 1
                    status = _RESTARTING
                    restart_at = now + backoff
            elif status == _RESTARTING and now >= restart_at:
                status = _ALIVE
                last_pong = now
                ingested = published    # re-ingest BEFORE serving
            elif status == _ALIVE:
                last_pong = now         # an alive worker always pongs
        return (now, status, last_pong, restart_at, n_restarts, next_rid,
                lo, n_served, n_unavail, published, ingested, subs_left,
                faults_left)

    def conservation(s: Any) -> bool:
        # every rid below lo answered exactly once, everything at or
        # above lo still in flight: served + unavailable == answered
        return s[7] + s[8] == s[6] and s[6] <= s[5]

    def rid_monotone(s: Any) -> bool:
        # restarts never rewind rid assignment (lo/next_rid only grow
        # by construction; EXHAUSTED leaves nothing stranded)
        if s[1] == _EXHAUSTED:
            return s[6] == s[5]
        return 0 <= s[6] <= s[5] <= submits

    def hang_detected(s: Any) -> bool:
        return s[1] != _HUNG or s[0] - s[2] <= deadline

    def publish_never_blocks(s: Any) -> bool:
        return s[9] >= publishes or "publish" in enabled(s)

    def ingest_monotone(s: Any) -> bool:
        return 0 <= s[10] <= s[9] <= publishes

    def budget_respected(s: Any) -> bool:
        return s[4] <= restarts

    return Model(
        name=f"supervisor[{mutant or 'faithful'}]",
        initial=initial, enabled=enabled, step=step,
        invariants={
            "request-conservation": conservation,
            "rid-monotone": rid_monotone,
            "hang-detected-within-deadline": hang_detected,
            "publish-never-blocks": publish_never_blocks,
            "reingest-monotone": ingest_monotone,
            "restart-budget-respected": budget_respected,
        })


#: the seeded protocol mutants the CLI must REFUTE (name -> builder)
MUTANTS: Dict[str, Callable[[], Model]] = {
    "seqlock:no_crc": lambda **kw: seqlock_model("no_crc", **kw),
    "seqlock:stamps_swapped":
        lambda **kw: seqlock_model("stamps_swapped", **kw),
    "supervisor:deadline_off_by_one":
        lambda **kw: supervisor_model("deadline_off_by_one", **kw),
}


# ------------------------------------------------------------ seeded drills

#: unguarded cross-thread mutation — MUST fire "unguarded-shared"
DRILL_UNGUARDED_SRC = '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop)
        self._thread.start()

    def _loop(self):
        while True:
            self._count += 1

    def bump(self):
        self._count += 1
'''

#: inconsistent two-lock order — MUST fire "lock-order-cycle"
DRILL_CYCLE_SRC = '''
import threading

class Transfer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
'''

#: sleep inside a critical section — MUST fire "blocking-under-lock"
DRILL_BLOCKING_SRC = '''
import threading
import time

class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0

    def poll(self):
        with self._lock:
            time.sleep(0.1)
            self._v += 1
'''


def run_drills(max_states: int = 500_000) -> List[str]:
    """Self-drill the auditor; returns failure strings (empty = pass).

    The Half-1 drills feed seeded-broken sources through the same
    analysis as the repo scan and require each finding kind to fire;
    the Half-2 drills require the faithful models to PROVE and every
    seeded mutant to be REFUTED — an explorer that can't tell a broken
    protocol from a correct one gates nothing."""
    failures: List[str] = []
    for name, src, kind in (
            ("unguarded-attribute", DRILL_UNGUARDED_SRC, "unguarded-shared"),
            ("lock-order-cycle", DRILL_CYCLE_SRC, "lock-order-cycle"),
            ("blocking-under-lock", DRILL_BLOCKING_SRC,
             "blocking-under-lock")):
        contract = ConcurrencyContract(module=f"<drill:{name}>",
                                       threads=("Worker.start",))
        rep = audit_source(src, f"<drill:{name}>")
        if not any(f.kind == kind for f in rep.findings):
            failures.append(
                f"drill '{name}' did not fire a {kind} finding")
    for model in (seqlock_model(), supervisor_model()):
        res = prove(model, max_states)
        if not res.ok:
            failures.append(f"faithful model failed to prove: {res}")
    for name, build in MUTANTS.items():
        res = refute(build(), max_states)
        if res.ok:
            failures.append(
                f"mutant '{name}' was NOT refuted — the explorer "
                f"cannot distinguish a broken protocol")
    return failures

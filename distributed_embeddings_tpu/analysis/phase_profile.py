"""Measured phase-time observatory: trace-parsed step attribution and
cost-model calibration against the schedule auditor.

Every phase number the repo had before this module was *modeled*:
:mod:`.schedule_audit` prices the compiled step's dependency DAG from
:data:`~.plan_audit.CHIP_SPECS` byte arithmetic, and the bench gates ride
those predictions. Nothing measured where a step's milliseconds actually
go — ``DETPU_PROFILE_DIR`` dumped raw TensorBoard traces no tool ever
read. This module closes the loop, with the same profile-then-optimize
discipline the reference library applies to its fused lookup kernels:

* :func:`profile_steps` runs N timed steps, each under its own
  ``jax.profiler.trace`` capture, parses every capture with the jax-free
  :mod:`~..utils.traceparse`, and reduces them to a
  :class:`PhaseProfile`: per-phase measured duration (p50/p95 over
  steps), the measured step breakdown (exchange vs lookup vs apply vs
  dense), the measured all-to-all fraction, measured overlap
  (wall-clock union vs summed phase durations), and a measured
  serialized-vs-overlapped verdict per exchange phase;
* :class:`HloPhaseIndex` joins bare-name trace events (this container's
  CPU backend carries no op metadata in its events) against the compiled
  module's OWN text — instruction name -> ``obs.scope`` phase via
  ``metadata.op_name``, the exact machinery the HLO census and schedule
  auditor share — and supplies each collective's DAG-**independent**
  compute spans, so "measured overlap" only credits compute a scheduler
  could genuinely have hidden the exchange under (concurrent-but-
  dependent work from lockstep skew across virtual devices does not
  count);
* :func:`calibrate` joins the measured per-phase durations against
  :class:`~.schedule_audit.ScheduleReport`'s modeled per-phase costs
  into a drift table — measured/modeled ratio per phase, normalized by
  the cost-weighted median ratio so a *uniform* backend-speed difference
  (CPU proxy vs the modeled v5e) cancels and what remains is relative
  mispricing — flagging phases beyond ``DETPU_PHASE_DRIFT_MAX`` (2x);
* :func:`check_agreement` is the classification cross-check the
  ``make phase-profile`` gate enforces: a collective the model calls
  **serialized** must measure serialized (if it measured overlapped, the
  model is lying about the dependency structure); a modeled
  **overlappable** collective may measure either way — structural
  possibility is not realized overlap until the pipelined step ships
  (ROADMAP item 2), and exactly this asymmetry makes the gate a ratchet:
  once the pipelined step wins real overlap, the measured classification
  flips and ``tools/compare_bench.py::check_phase_profile`` refuses to
  let it regress.

Profiling is strictly opt-in: nothing here touches how steps are built —
an unprofiled step is bitwise the program it always was, and the bench's
``phase_profile`` section prices the profiler's own overhead.

Module-scope imports stay jax-free (the dataclasses and the calibration
math must be importable by report tooling without a backend); everything
that lowers or traces imports jax lazily.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import envvars, traceparse
from ..utils.obs import phase_leaf

PROFILE_STEPS_ENV = "DETPU_PHASE_PROFILE_STEPS"
PROFILE_DIR_OVERRIDE_ENV = "DETPU_PHASE_PROFILE_DIR"
DRIFT_MAX_ENV = "DETPU_PHASE_DRIFT_MAX"

#: phases below this share of the step (both measured AND modeled) are
#: reported but never drift-flagged — ratio noise on a 0.1% phase is not
#: a mispricing signal
CALIBRATION_MIN_SHARE = 0.005


class PhaseProfileError(RuntimeError):
    """An unusable capture (no events parsed, no trace files) or a
    strict-mode agreement failure."""


# ---------------------------------------------------------- HLO phase join


class HloPhaseIndex:
    """Instruction-name -> phase resolver + DAG-independence spans, built
    from the compiled module's own text.

    The bare-name join: every trace event named like an HLO instruction
    (``all-to-all.6``, ``cosine_add_fusion.clone``, the ``copy``/``add``
    internals of a while-lowered scatter) resolves to the phase of its
    instruction — the instruction's own ``metadata.op_name`` scope when
    present, else the resolved phase of the ENTRY instruction that
    (transitively) calls its computation, so fusion and loop-body
    internals inherit their parent op's phase instead of polluting
    "(unscoped)".
    """

    def __init__(self, hlo_text: str, *, world: int = 1,
                 chip: str = "v5e"):
        from .plan_audit import CHIP_SPECS
        from .schedule_audit import ScheduleGraph, parse_hlo_module

        comps = parse_hlo_module(hlo_text)
        self.graph = ScheduleGraph(comps, world=world,
                                   chip=CHIP_SPECS[chip])
        self._phase: Dict[str, str] = {}
        self._entry: Dict[str, int] = {}
        # transitive computation ownership: comp name -> entry node
        # indices whose instruction (chain) calls it
        owners: Dict[str, set] = {}
        for node in self.graph.nodes:
            stack = list(node.instr.called)
            seen: set = set()
            while stack:
                cname = stack.pop()
                if cname in seen:
                    continue
                seen.add(cname)
                owners.setdefault(cname, set()).add(node.index)
                comp = comps.get(cname)
                if comp is None:
                    continue
                for inner in comp.instructions:
                    stack.extend(inner.called)
        for node in self.graph.nodes:
            self._phase[node.instr.name] = node.phase
            self._entry[node.instr.name] = node.index
        for cname, comp in comps.items():
            if comp.is_entry:
                continue
            own = owners.get(cname, set())
            entry = next(iter(own)) if len(own) == 1 else None
            for inner in comp.instructions:
                phase = inner.phase
                if not phase and entry is not None:
                    phase = self.graph.nodes[entry].phase
                # entry instruction names win on (rare) collisions
                self._phase.setdefault(inner.name, phase)
                if entry is not None:
                    self._entry.setdefault(inner.name, entry)

    def resolve(self, name: str) -> Optional[str]:
        """Phase of one event/instruction name; ``None`` when the name is
        not an instruction of this module (the event stays unattributed —
        it still counts toward wall time)."""
        hit = self._phase.get(name)
        if hit is None and name.endswith(".clone"):
            hit = self._phase.get(name[: -len(".clone")])
        return hit

    def entry_of(self, name: str) -> Optional[int]:
        hit = self._entry.get(name)
        if hit is None and name.endswith(".clone"):
            hit = self._entry.get(name[: -len(".clone")])
        return hit

    def independent_spans(self, events: Sequence[traceparse.TraceEvent]
                          ) -> Dict[str, List[Tuple[float, float]]]:
        """Per collective phase: merged wall-clock spans of the events of
        entry nodes that are DAG-independent of EVERY collective in that
        phase (outside all their ancestor/descendant cones, non-trivial,
        non-collective) — the compute a latency-hiding schedule could
        genuinely have run under the exchange. Feeding these to
        :func:`~..utils.traceparse.measure_events` makes the measured
        serialized/overlapped verdict dependency-aware instead of
        crediting lockstep skew."""
        g = self.graph
        by_phase_nodes: Dict[str, List[int]] = {}
        for n in g.nodes:
            if n.is_collective and n.phase:
                by_phase_nodes.setdefault(n.phase, []).append(n.index)
        by_entry_events: Dict[int, List[traceparse.TraceEvent]] = {}
        for e in events:
            idx = self.entry_of(e.name.lstrip("%"))
            if idx is not None:
                by_entry_events.setdefault(idx, []).append(e)
        out: Dict[str, List[Tuple[float, float]]] = {}
        for phase, colls in by_phase_nodes.items():
            excluded: set = set()
            for c in colls:
                excluded |= g.ancestors(c) | g.descendants(c) | {c}
            spans: List[Tuple[float, float]] = []
            for n in g.nodes:
                if (n.index in excluded or n.is_collective
                        or n.is_trivial):
                    continue
                for e in by_entry_events.get(n.index, ()):
                    spans.append((e.ts, e.end))
            out[phase] = traceparse.merge_intervals(spans)
        return out


# -------------------------------------------------------------- the report


def _pct(xs: Sequence[float], q: float) -> float:
    """Percentile without numpy (nearest-rank on the sorted sample —
    exact enough for 3-20 step samples and keeps this module jax/numpy
    free)."""
    if not xs:
        return 0.0
    s = sorted(xs)
    k = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
    return float(s[k])


@dataclasses.dataclass
class PhaseProfile:
    """Measured per-phase timing of N profiled steps (the measured
    counterpart of :class:`~.schedule_audit.ScheduleReport`)."""
    label: str
    steps: int
    world: int
    backend: Optional[str]
    #: per detpu phase path: {"p50"/"p95"/"mean" ms summed over events}
    phase_ms: Dict[str, Dict[str, float]]
    #: per step-attribution group (exchange/lookup/dense/apply/...): p50 ms
    group_ms: Dict[str, float]
    step_wall_ms: Dict[str, float]          # p50/p95 busy wall clock
    concurrency: float                      # p50 busy/wall
    a2a_frac: float                         # p50 exchange-in-flight frac
    measured_serialized_fraction: Optional[float]   # p50 over steps
    #: per exchange phase: majority classification + p50 hidden_frac
    collectives: List[Dict[str, Any]]
    events_per_step: float
    resolved_frac: float                    # event-attribution coverage
    per_step: List[Dict[str, Any]]          # raw per-step measurements
    #: p50 wall seconds of one step under capture (the profiler's cost on
    #: the step itself; parsing is off the training path and priced
    #: separately in parse_s)
    capture_s: Optional[float] = None
    parse_s: Optional[float] = None

    @classmethod
    def from_steps(cls, measures: List[Dict[str, Any]], *, label: str,
                   world: int, backend: Optional[str]) -> "PhaseProfile":
        if not measures:
            raise PhaseProfileError(
                f"phase profile {label!r}: no step captures to reduce")
        phases = sorted({p for m in measures for p in m["phase_ms"]})
        phase_ms = {}
        for p in phases:
            xs = [m["phase_ms"].get(p, 0.0) for m in measures]
            phase_ms[p] = {"p50": round(_pct(xs, 50), 4),
                           "p95": round(_pct(xs, 95), 4),
                           "mean": round(sum(xs) / len(xs), 4)}
        group_ms = {g: round(_pct([m["group_ms"].get(g, 0.0)
                                   for m in measures], 50), 4)
                    for g in traceparse.GROUPS}
        walls = [m["wall_ms"] for m in measures]
        fracs = [m["measured_serialized_fraction"] for m in measures
                 if m["measured_serialized_fraction"] is not None]
        coll_phases = sorted({c["phase"] for m in measures
                              for c in m["collectives"]})
        collectives = []
        for p in coll_phases:
            rows = [c for m in measures for c in m["collectives"]
                    if c["phase"] == p]
            n_ser = sum(r["classification"] == "serialized" for r in rows)
            collectives.append({
                "phase": p,
                "union_ms": round(_pct([r["union_ms"] for r in rows], 50),
                                  4),
                "hidden_frac": round(_pct([r["hidden_frac"]
                                           for r in rows], 50), 4),
                "classification": ("serialized" if 2 * n_ser >= len(rows)
                                   else "overlapped"),
                "samples": len(rows),
            })
        n_ev = [m["events"] for m in measures]
        n_res = [m["events_resolved"] for m in measures]
        caps = [m["capture_s"] for m in measures if "capture_s" in m]
        parses = [m["parse_s"] for m in measures if "parse_s" in m]
        return cls(
            capture_s=round(_pct(caps, 50), 4) if caps else None,
            parse_s=round(_pct(parses, 50), 4) if parses else None,
            label=label, steps=len(measures), world=world, backend=backend,
            phase_ms=phase_ms, group_ms=group_ms,
            step_wall_ms={"p50": round(_pct(walls, 50), 4),
                          "p95": round(_pct(walls, 95), 4)},
            concurrency=round(_pct([m["concurrency"] for m in measures],
                                   50), 4),
            a2a_frac=round(_pct([m["a2a_frac"] for m in measures], 50), 4),
            measured_serialized_fraction=(
                round(_pct(fracs, 50), 4) if fracs else None),
            collectives=collectives,
            events_per_step=round(sum(n_ev) / len(n_ev), 1),
            resolved_frac=round(sum(n_res) / max(sum(n_ev), 1), 4),
            per_step=measures)

    def to_json(self, include_steps: bool = False) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if not include_steps:
            d.pop("per_step")
        return d

    def summary(self) -> Dict[str, Any]:
        """The compact record the bench ``phase_profile`` section embeds
        (and ``check_phase_profile`` gates)."""
        return {
            "label": self.label,
            "world": self.world,
            "backend": self.backend,
            "steps": self.steps,
            "step_wall_ms_p50": self.step_wall_ms["p50"],
            "group_ms": dict(self.group_ms),
            "a2a_frac": self.a2a_frac,
            "concurrency": self.concurrency,
            "measured_serialized_fraction":
                self.measured_serialized_fraction,
            "collectives": [
                {"phase": c["phase"],
                 "classification": c["classification"],
                 "hidden_frac": c["hidden_frac"]}
                for c in self.collectives],
            "resolved_frac": self.resolved_frac,
        }

    def markdown(self) -> str:
        lines = [
            f"measured phase profile `{self.label}` — {self.steps} steps, "
            f"world {self.world}, backend {self.backend or '?'}:",
            "",
            "| phase | p50 ms | p95 ms |",
            "|---|---|---|",
        ]
        order = sorted(self.phase_ms,
                       key=lambda p: -self.phase_ms[p]["p50"])
        for p in order:
            row = self.phase_ms[p]
            lines.append(f"| `{p}` | {row['p50']:.3f} | {row['p95']:.3f} |")
        lines.append("")
        lines.append(
            "breakdown (p50 ms): " + ", ".join(
                f"{g}={self.group_ms.get(g, 0.0):.3f}"
                for g in traceparse.GROUPS))
        lines.append(
            f"step wall p50 {self.step_wall_ms['p50']:.3f} ms | "
            f"concurrency x{self.concurrency:.2f} | a2a in flight "
            f"{self.a2a_frac * 100:.1f}% | measured serialized fraction "
            + (f"{self.measured_serialized_fraction:.3f}"
               if self.measured_serialized_fraction is not None else "n/a"))
        for c in self.collectives:
            lines.append(
                f"  - `{c['phase']}`: **{c['classification']}** "
                f"(hidden {c['hidden_frac'] * 100:.1f}% of "
                f"{c['union_ms']:.3f} ms in flight)")
        return "\n".join(lines)


# ------------------------------------------------------------- the harness


def default_profile_steps() -> int:
    return max(1, envvars.get_int(PROFILE_STEPS_ENV))


def profile_steps(run_step: Callable[[], Any], *,
                  steps: Optional[int] = None,
                  profile_dir: Optional[str] = None,
                  index: Optional[HloPhaseIndex] = None,
                  world: int = 1,
                  label: str = "step",
                  overlap_min_frac: float = 0.5) -> PhaseProfile:
    """Capture and reduce N profiled steps.

    ``run_step`` runs exactly one already-compiled step AND blocks on its
    result (the caller owns state threading and the readback — the same
    contract as the bench's timed loops). Each step gets its OWN
    ``jax.profiler.trace`` capture so the per-phase numbers carry real
    p50/p95 spread instead of one blurred total. Captures land under
    ``profile_dir`` (default ``DETPU_PHASE_PROFILE_DIR``, else a temp
    directory deleted after parsing — set the env var to keep
    TensorBoard-loadable traces).

    Profiling is opt-in by construction: this wraps EXECUTION only; the
    step program is whatever the caller compiled, bitwise.
    """
    import jax

    steps = default_profile_steps() if steps is None else max(1, steps)
    base = profile_dir or envvars.get(PROFILE_DIR_OVERRIDE_ENV)
    cleanup = base is None
    if base is None:
        base = tempfile.mkdtemp(prefix="detpu_phase_profile_")
    resolver = index.resolve if index is not None else None
    try:
        # throwaway warm-up capture: the process's FIRST profiler
        # session pays a multi-second one-time init that would skew the
        # first step's p95 by two orders of magnitude
        warm = os.path.join(base, label.replace("/", "_"), "_warmup")
        os.makedirs(warm, exist_ok=True)
        with jax.profiler.trace(warm):
            run_step()
        shutil.rmtree(warm, ignore_errors=True)
        measures = []
        for k in range(steps):
            d = os.path.join(base, label.replace("/", "_"),
                             f"step{k:03d}")
            os.makedirs(d, exist_ok=True)
            t0 = time.perf_counter()
            with jax.profiler.trace(d):
                run_step()
            t_cap = time.perf_counter() - t0
            events = traceparse.parse_capture(d, resolver=resolver)
            if not events:
                raise PhaseProfileError(
                    f"phase profile {label!r}: step {k} capture at {d} "
                    "parsed 0 op events — unrecognized trace format or "
                    "an empty capture; the measured gate cannot run on it")
            ind = (index.independent_spans(events)
                   if index is not None else None)
            m = traceparse.measure_events(
                events, independent_spans=ind,
                overlap_min_frac=overlap_min_frac)
            m["capture_s"] = round(t_cap, 4)
            m["parse_s"] = round(time.perf_counter() - t0 - t_cap, 4)
            measures.append(m)
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - stamp is best-effort
            backend = None
        return PhaseProfile.from_steps(measures, label=label, world=world,
                                       backend=backend)
    finally:
        if cleanup:
            shutil.rmtree(base, ignore_errors=True)


# ---------------------------------------------------------- calibration


@dataclasses.dataclass
class CalibrationRow:
    phase: str
    measured_ms: float
    modeled_ms: float            # schedule auditor cost, ns -> ms
    ratio: Optional[float]       # measured / modeled
    normalized: Optional[float]  # ratio / cost-weighted median ratio
    share_measured: float
    share_modeled: float
    flagged: bool


@dataclasses.dataclass
class CalibrationReport:
    """The measured-vs-modeled drift table: where the byte-cost model
    that prices every bench gate drifts from the clock."""
    label: str
    rows: List[CalibrationRow]
    scale: float                 # the cancelled backend-speed factor
    drift_max: float
    flagged: List[str]

    @property
    def ok(self) -> bool:
        return not self.flagged

    def to_json(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "scale_measured_over_modeled": round(self.scale, 4),
            "drift_max": self.drift_max,
            "flagged": list(self.flagged),
            "rows": [dataclasses.asdict(r) for r in self.rows],
        }

    def markdown(self) -> str:
        lines = [
            f"calibration `{self.label}` — backend-speed scale "
            f"x{self.scale:.2f} cancelled; flag at >{self.drift_max:g}x "
            "relative drift:",
            "",
            "| phase | measured ms | modeled ms | ratio | vs median | |",
            "|---|---|---|---|---|---|",
        ]
        for r in self.rows:
            lines.append(
                f"| `{r.phase}` | {r.measured_ms:.3f} | "
                f"{r.modeled_ms:.4f} | "
                + (f"{r.ratio:.1f}x" if r.ratio is not None else "—")
                + " | "
                + (f"{r.normalized:.2f}x" if r.normalized is not None
                   else "—")
                + (" | **DRIFT** |" if r.flagged else " | |"))
        if self.flagged:
            lines.append("")
            lines.extend(f"- DRIFT: {f}" for f in self.flagged)
        return "\n".join(lines)


def calibrate(profile: PhaseProfile, schedule_report,
              drift_max: Optional[float] = None,
              label: Optional[str] = None) -> CalibrationReport:
    """Join measured per-phase p50 durations against the schedule
    auditor's modeled per-phase costs (``ScheduleReport.phase_cost_ns``).

    Measured and modeled run on different clocks (a CPU-proxy capture vs
    the v5e byte model), so the RAW ratio is dominated by backend speed.
    The drift table therefore normalizes every phase's ratio by the
    cost-weighted median ratio: a phase whose normalized ratio exceeds
    ``drift_max`` (``DETPU_PHASE_DRIFT_MAX``, default 2x) costs that much
    more — or less, below ``1/drift_max`` — than the model believes
    *relative to the other phases*, which is exactly the mispricing that
    would mislead a CHIP_SPECS-gated decision. Phases below
    :data:`CALIBRATION_MIN_SHARE` of the step on both sides are reported
    but never flagged."""
    if drift_max is None:
        drift_max = envvars.get_float(DRIFT_MAX_ENV)
        if drift_max <= 0:
            drift_max = 2.0
    modeled = {p: ns / 1e6 for p, ns in
               getattr(schedule_report, "phase_cost_ns", {}).items() if ns}
    measured = {p: v["p50"] for p, v in profile.phase_ms.items()}
    tot_meas = sum(measured.values()) or 1.0
    tot_mod = sum(modeled.values()) or 1.0
    phases = sorted(set(measured) | set(modeled),
                    key=lambda p: -(measured.get(p, 0.0)))
    # cost-weighted median of measured/modeled over phases both sides see
    pairs = [(measured[p] / modeled[p], modeled[p])
             for p in phases
             if p in measured and p in modeled and modeled[p] > 0
             and measured[p] > 0]
    scale = 1.0
    if pairs:
        pairs.sort()
        half = sum(w for _, w in pairs) / 2.0
        acc = 0.0
        for ratio, w in pairs:
            acc += w
            if acc >= half:
                scale = ratio
                break
    rows: List[CalibrationRow] = []
    flagged: List[str] = []
    for p in phases:
        if p in ("(unscoped)", ""):
            continue
        meas = measured.get(p, 0.0)
        mod = modeled.get(p, 0.0)
        ratio = meas / mod if mod > 0 and meas > 0 else None
        norm = ratio / scale if ratio is not None and scale > 0 else None
        sm, so = meas / tot_meas, mod / tot_mod
        flag = bool(
            norm is not None
            and (norm > drift_max or norm < 1.0 / drift_max)
            and max(sm, so) >= CALIBRATION_MIN_SHARE)
        rows.append(CalibrationRow(
            phase=p, measured_ms=round(meas, 4), modeled_ms=round(mod, 4),
            ratio=None if ratio is None else round(ratio, 3),
            normalized=None if norm is None else round(norm, 3),
            share_measured=round(sm, 4), share_modeled=round(so, 4),
            flagged=flag))
        if flag:
            flagged.append(
                f"phase '{p}': measured/modeled {ratio:.1f}x is "
                f"{norm:.2f}x the step's median {scale:.1f}x — the byte "
                f"model misprices this phase beyond {drift_max:g}x "
                f"({meas:.3f} ms measured vs {mod:.4f} ms modeled)")
    return CalibrationReport(
        label=label or profile.label, rows=rows, scale=scale,
        drift_max=drift_max, flagged=flagged)


# ------------------------------------------------------------- agreement


def check_agreement(profile: PhaseProfile, schedule_report) -> List[str]:
    """Measured-vs-modeled classification cross-check (the acceptance
    contract of ``make phase-profile``):

    * every collective phase the schedule auditor classifies
      **serialized** must exist in the measured profile AND measure
      serialized — a measured overlap on a modeled-serialized exchange
      means the model's dependency cones are wrong;
    * a modeled **overlappable** collective may measure either way (the
      unpipelined step is free to serialize what is merely possible);
    * a measured exchange phase the model never saw is a join failure
      worth failing on (the two views drifted onto different programs).

    Only EXCHANGE phases (``*all_to_all*`` — the step schedule's
    collective phases) are compared: the psum all-reduces (loss pmean,
    nan-guard verdict) are collectives to the DAG model but are not part
    of the overlap contract, and the measured side deliberately counts
    only exchanges.

    Returns violation strings; empty = agreement.
    """
    out: List[str] = []
    modeled: Dict[str, List[str]] = {}
    for c in schedule_report.collectives:
        if not traceparse.is_collective_phase(c.phase):
            continue
        modeled.setdefault(c.phase, []).append(c.classification)
    measured = {c["phase"]: c["classification"]
                for c in profile.collectives}
    for phase, cls_list in sorted(modeled.items()):
        got = measured.get(phase)
        if got is None:
            out.append(
                f"agreement: modeled collective phase '{phase}' never "
                "appeared in the measured capture — trace too coarse, "
                "phase renamed, or the profiled program is not the "
                "audited one")
            continue
        if "serialized" in cls_list and got != "serialized":
            out.append(
                f"agreement: phase '{phase}' is modeled SERIALIZED but "
                f"measured {got.upper()} — the cost model's dependency "
                "cones disagree with the clock")
    for phase in sorted(measured):
        if phase not in modeled:
            out.append(
                f"agreement: measured exchange phase '{phase}' is not a "
                "collective of the modeled schedule graph — the measured "
                "and modeled views audit different programs")
    return out

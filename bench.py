"""Headline benchmark: DLRM train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "dlrm_samples_per_sec_per_chip", "value": N, "unit": "samples/s",
   "vs_baseline": N}

Config mirrors the reference's DLRM example (``examples/dlrm/``: MLPerf DLRM,
26 categorical features, embedding dim 128, bottom MLP 512-256-128, top MLP
1024-1024-512-256-1, SGD, global batch 65536) with Criteo-Kaggle-like vocab
sizes frequency-capped at 2M rows so the tables (~5.4 GB fp32) fit a single
chip's HBM — the single-chip slice of the Criteo-1TB target.

Baseline: the north-star from BASELINE.json — DLRM Criteo-1TB at >=2M
samples/s on v5e-16, i.e. 125k samples/s/chip. vs_baseline = value / 125000.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.models.dlrm import (
    DLRMConfig, DLRMDense, bce_with_logits)
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, HybridTrainState, SparseSGD, make_hybrid_train_step)
from distributed_embeddings_tpu.utils import power_law_ids

CRITEO_KAGGLE_SIZES = [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
]
CAP = 2_000_000
BATCH = 65536
BASELINE_SAMPLES_PER_SEC_PER_CHIP = 125_000.0


def main():
    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg = DLRMConfig(table_sizes=table_sizes, embedding_dim=128,
                     num_numerical_features=13,
                     bottom_mlp_dims=(512, 256, 128),
                     top_mlp_dims=(1024, 1024, 512, 256, 1))

    de = DistributedEmbedding(cfg.embedding_configs(), world_size=1)
    dense = DLRMDense(cfg)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)

    rng = np.random.default_rng(0)
    num = jnp.asarray(rng.normal(size=(BATCH, 13)), jnp.float32)
    cats = [jnp.asarray(power_law_ids(rng, s, (BATCH,)), jnp.int32)
            for s in table_sizes]
    labels = jnp.asarray(rng.integers(0, 2, size=(BATCH, 1)), jnp.float32)

    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32) for _ in table_sizes])

    flat = de.init(jax.random.key(1))
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense_params,
        dense_opt_state=tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.005)

    # warmup / compile
    for _ in range(3):
        loss, state = step_fn(state, cats, (num, labels))
    jax.block_until_ready(loss)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, state = step_fn(state, cats, (num, labels))
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / iters

    samples_per_sec = BATCH / dt
    print(json.dumps({
        "metric": "dlrm_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec /
                             BASELINE_SAMPLES_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()

"""Headline benchmark: DLRM train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "dlrm_samples_per_sec_per_chip", "value": N, "unit": "samples/s",
   "vs_baseline": N, ...extras}

Config mirrors the reference's DLRM example (``examples/dlrm/``: MLPerf DLRM,
26 categorical features, embedding dim 128, bottom MLP 512-256-128, top MLP
1024-1024-512-256-1, SGD, global batch 65536). Variants:

* capped fp32 / bf16-compute: Criteo-Kaggle vocabs frequency-capped at 2M
  rows (~5.4 GB fp32) — the round-1/2-comparable headline;
* **uncapped bf16**: the full Criteo-Kaggle vocab sizes (33.8M rows,
  ~8.3 GB bf16 tables) — no cap, the sizes the dataset actually has;
* **multi-hot ragged**: DCNv2-style variable hotness (1..30 ids per
  feature, mean ~15.5) through the static-capacity ``Ragged`` path;
* tiny-zoo Adagrad/SGD (BASELINE.md's synthetic table, 55 tables, 4.3 GB).

Timing: threaded-state loop with a **value readback** at the end.
``jax.block_until_ready`` is a NO-OP through this environment's device
tunnel (measured: a 2.8M-row scatter "completed" in 0.1 ms until the value
was fetched), so the loop forces completion with ``float(loss)`` — one
scalar readback whose ~0.1 s tunnel constant is amortized over the loop.

Also emits a v5e-16 step-time budget (analytic ICI exchange cost on top of
measured single-chip pieces; see ``docs/perf_tpu.md``) that makes the
north-star ">=2M samples/s on v5e-16" claim checkable.

Baseline: BASELINE.json north star — DLRM Criteo at >=2M samples/s on
v5e-16, i.e. 125k samples/s/chip. vs_baseline = value / 125000.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.models.dlrm import (
    DLRMConfig, DLRMDense, bce_with_logits)
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, HybridTrainState, SparseSGD, make_hybrid_train_step)
from distributed_embeddings_tpu.utils import power_law_ids

CRITEO_KAGGLE_SIZES = [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
]
CAP = 2_000_000
BATCH = 65536
BASELINE_SAMPLES_PER_SEC_PER_CHIP = 125_000.0
# TPU v5e (v5 lite): 197 TFLOP/s bf16 peak, 819 GB/s HBM, ~100 GB/s
# effective per-chip all-to-all bandwidth over ICI (2D torus, 4x 400 Gbps
# links; conservative effective figure).
V5E_BF16_PEAK_FLOPS = 197e12
V5E_HBM_GBPS = 819.0
V5E_ICI_EFF_GBPS = 100.0


def timed_loop(step, state, args, iters=24, warmup=3):
    """Threaded-state timing with forced completion via value readback."""
    loss = None
    for _ in range(warmup):
        loss, state = step(state, *args)
    float(loss)  # drain the pipeline before starting the clock
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, state = step(state, *args)
    float(loss)  # forces execution of the whole chain (tunnel-safe)
    dt = (time.perf_counter() - t0) / iters
    del state
    return dt


def dense_flops_per_sample(cfg, num_tables):
    """Fwd matmul FLOPs/sample; training ~3x (fwd + dgrad + wgrad)."""
    dims = [cfg.num_numerical_features] + cfg.bottom_mlp_dims
    f = sum(2 * a * b for a, b in zip(dims, dims[1:]))
    nf = num_tables + 1
    f += 2 * nf * nf * cfg.embedding_dim  # dot interaction gram
    top_in = nf * (nf - 1) // 2 + cfg.embedding_dim
    dims = [top_in] + cfg.top_mlp_dims
    f += sum(2 * a * b for a, b in zip(dims, dims[1:]))
    return 3 * f


def embedding_hbm_bytes_per_sample(num_tables, dim, param_bytes=4,
                                   hotness=1.0):
    """Rough embedding-table HBM traffic per sample: fwd row gather + SGD
    update read-modify-write of the touched row."""
    row = dim * param_bytes
    return num_tables * hotness * row * 3


def make_cfg(table_sizes, compute_dtype):
    """The one benchmarked model config — also the probe for the FLOPs and
    HBM-traffic estimates, so the timed model and the roofline math can't
    drift apart."""
    return DLRMConfig(table_sizes=table_sizes, embedding_dim=128,
                      num_numerical_features=13,
                      bottom_mlp_dims=(512, 256, 128),
                      top_mlp_dims=(1024, 1024, 512, 256, 1),
                      compute_dtype=compute_dtype)


def build_state(de, dense, cfg, emb_opt, tx, table_sizes, param_dtype,
                batch=None):
    batch = BATCH if batch is None else batch
    rng = np.random.default_rng(0)
    num = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, size=(batch, 1)), jnp.float32)
    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32) for _ in table_sizes])
    flat = de.init(jax.random.key(1), dtype=param_dtype)
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense_params,
        dense_opt_state=tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))
    return state, num, labels


def run_dlrm(table_sizes, compute_dtype, param_dtype=jnp.float32,
             ragged_hotness=None, batch=None):
    """One DLRM variant; returns samples/s. ``ragged_hotness`` switches the
    26 features to variable-hotness Ragged inputs with that mean hotness."""
    batch = BATCH if batch is None else batch
    combiner = "sum" if ragged_hotness else None
    cfg = make_cfg(table_sizes, compute_dtype)
    de = DistributedEmbedding(cfg.embedding_configs(combiner=combiner),
                              world_size=1, compute_dtype=compute_dtype)
    dense = DLRMDense(cfg)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)

    rng = np.random.default_rng(0)
    if ragged_hotness is None:
        cats = [jnp.asarray(power_law_ids(rng, s, (batch,)), jnp.int32)
                for s in table_sizes]
    else:
        # near-exact capacity: the reference's dynamic ragged carries no
        # padding, so minimal static headroom is the fair equivalent (every
        # padded position costs full gather/scatter price on TPU). One
        # UNIFORM capacity (max feature nnz, < 1% over the mean at this
        # batch) lets the plan executor batch all 26 features into a single
        # (width, capacity) group — one gather + one combine total.
        draws = []
        for s in table_sizes:
            hots = rng.integers(1, 2 * ragged_hotness + 1, size=batch)
            splits = np.zeros(batch + 1, np.int32)
            np.cumsum(hots, out=splits[1:])
            draws.append((s, splits))
        cap = max(int(sp[-1]) for _, sp in draws)
        cats = []
        for s, splits in draws:
            nnz = int(splits[-1])
            vals = np.zeros(cap, np.int32)
            vals[:nnz] = power_law_ids(rng, s, (nnz,))
            cats.append(Ragged(values=jnp.asarray(vals),
                               row_splits=jnp.asarray(splits)))

    state, num, labels = build_state(de, dense, cfg, emb_opt, tx,
                                     table_sizes, param_dtype, batch=batch)

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.005)
    dt = timed_loop(step_fn, state, (cats, (num, labels)))
    return batch / dt


def run_tiny_zoo(opt_name):
    """Synthetic `tiny` zoo model (55 tables, 4.3 GB uncapped, batch 65536)
    — BASELINE.md's main table; the reference's 1xA100 Adagrad number is
    24.433 ms/iter (`synthetic_models/README.md:69`)."""
    from distributed_embeddings_tpu.models import (
        InputGenerator, build_synthetic, synthetic_models_v3)
    from distributed_embeddings_tpu.parallel import (
        SparseAdagrad, init_hybrid_state)

    mc = synthetic_models_v3["tiny"]
    de, dense, _ = build_synthetic(mc, 1)
    gen = InputGenerator(mc, BATCH, alpha=1.05, num_batches=1)
    if opt_name == "adagrad":
        emb_opt, tx = SparseAdagrad(), optax.adagrad(0.01)
    else:
        emb_opt, tx = SparseSGD(), optax.sgd(0.01)
    num, cats, labels = gen[0]
    out_widths = [int(de.strategy.global_configs[t]["output_dim"])
                  for t in de.strategy.input_table_map]
    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, w), jnp.float32) for w in out_widths])

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return jnp.mean((dense.apply(dp, n, emb_outs) - y) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1))
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.01)
    dt = timed_loop(step_fn, state, (cats, (num, labels)), iters=15)
    return dt * 1e3


def v5e16_budget(single_chip_samples_per_sec, num_tables, dim, world=16):
    """Analytic v5e-16 step-time budget from the measured single-chip step.

    Model (see docs/perf_tpu.md "v5e-16 budget"): per-chip compute (dense
    MLP on the 1/world batch shard + embedding lookups/updates for the
    global batch over 1/world of the tables) scales ~1/world from the
    measured single-chip step; on top ride the two all-to-alls (bf16
    activations fwd + grads bwd) and the int32 id exchange over ICI.
    """
    b_local = BATCH // world
    t_compute = (1.0 / single_chip_samples_per_sec) * BATCH / world
    a2a_bytes = (
        2 * (b_local * num_tables * dim * 2) * (world - 1) / world  # fwd+bwd
        + b_local * num_tables * 4 * (world - 1) / world)           # ids
    t_ici = a2a_bytes / (V5E_ICI_EFF_GBPS * 1e9)
    t_step = t_compute + t_ici
    return {
        "v5e16_budget_ms": round(t_step * 1e3, 3),
        "v5e16_a2a_mb_per_chip": round(a2a_bytes / 1e6, 2),
        "v5e16_projected_samples_per_sec": round(BATCH / t_step, 0),
    }


def _guard(name, fn, default=None):
    """One failed variant must not kill the whole benchmark report."""
    import traceback
    try:
        return fn()
    except Exception:  # noqa: BLE001 - report and continue
        import sys
        print(f"[bench] variant {name} failed:", file=sys.stderr)
        traceback.print_exc()
        return default


def main():
    capped = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg_probe = make_cfg(capped, jnp.bfloat16)

    fp32 = _guard("fp32", lambda: run_dlrm(capped, jnp.float32), 0.0)
    bf16 = _guard("bf16", lambda: run_dlrm(capped, jnp.bfloat16), 0.0)
    # full Criteo-Kaggle vocabs, bf16 tables (~8.3 GB) — no cap
    uncapped_bf16 = _guard(
        "uncapped_bf16",
        lambda: run_dlrm(CRITEO_KAGGLE_SIZES, jnp.bfloat16,
                         param_dtype=jnp.bfloat16))
    # DCNv2-style multi-hot ragged lookups (hotness 1..30, mean ~15.5).
    # Batch 16384: this environment's chipless remote compiler crashes on
    # the larger ragged program (a toolchain limit — the same program
    # compiles on the CPU backend); samples/s is batch-insensitive here.
    ragged = _guard("multihot_ragged", lambda: run_dlrm(
        capped, jnp.bfloat16, ragged_hotness=15, batch=16384))
    tiny_adagrad_ms = _guard("tiny_adagrad",
                             lambda: run_tiny_zoo("adagrad"))
    tiny_sgd_ms = _guard("tiny_sgd", lambda: run_tiny_zoo("sgd"))
    best = max(fp32, bf16)

    flops = dense_flops_per_sample(cfg_probe, len(capped))
    ebytes = embedding_hbm_bytes_per_sample(len(capped),
                                            cfg_probe.embedding_dim)
    def r(x, nd=1):
        return None if x is None else round(x, nd)

    out = {
        "metric": "dlrm_samples_per_sec_per_chip",
        "value": round(best, 1),
        "unit": "samples/s",
        "vs_baseline": round(best / BASELINE_SAMPLES_PER_SEC_PER_CHIP, 3),
        "variant": "bf16" if bf16 >= fp32 else "fp32",
        "fp32_samples_per_sec": round(fp32, 1),
        "bf16_samples_per_sec": round(bf16, 1),
        "uncapped_bf16_samples_per_sec": r(uncapped_bf16),
        "multihot_ragged_samples_per_sec": r(ragged),
        "multihot_mean_hotness": 15.5,
        "dense_mfu_bf16_est": round(flops * bf16 / V5E_BF16_PEAK_FLOPS, 4),
        "embedding_hbm_gbps_est": round(ebytes * best / 1e9, 1),
        "embedding_hbm_util_est": round(ebytes * best / 1e9 / V5E_HBM_GBPS,
                                        4),
        "tiny_zoo_adagrad_ms_per_iter": r(tiny_adagrad_ms),
        "tiny_zoo_sgd_ms_per_iter": r(tiny_sgd_ms),
        "tiny_zoo_vs_a100_1gpu": (
            None if tiny_adagrad_ms is None
            else round(24.433 / tiny_adagrad_ms, 3)),
    }
    if best > 0:
        out.update(v5e16_budget(best, len(capped), cfg_probe.embedding_dim))
    print(json.dumps(out))


if __name__ == "__main__":
    main()

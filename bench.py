"""Headline benchmark: DLRM train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "dlrm_samples_per_sec_per_chip", "value": N, "unit": "samples/s",
   "vs_baseline": N, ...extras}

Config mirrors the reference's DLRM example (``examples/dlrm/``: MLPerf DLRM,
26 categorical features, embedding dim 128, bottom MLP 512-256-128, top MLP
1024-1024-512-256-1, SGD, global batch 65536). Variants:

* capped fp32 / bf16-compute: Criteo-Kaggle vocabs frequency-capped at 2M
  rows (~5.4 GB fp32) — the round-1/2-comparable headline;
* **uncapped bf16**: the full Criteo-Kaggle vocab sizes (33.8M rows,
  ~8.3 GB bf16 tables) — no cap, the sizes the dataset actually has;
* **multi-hot ragged**: DCNv2-style variable hotness (1..30 ids per
  feature, mean ~15.5) through the static-capacity ``Ragged`` path;
* tiny-zoo Adagrad/SGD (BASELINE.md's synthetic table, 55 tables, 4.3 GB).

Timing: threaded-state loop with a **value readback** at the end.
``jax.block_until_ready`` is a NO-OP through this environment's device
tunnel (measured: a 2.8M-row scatter "completed" in 0.1 ms until the value
was fetched), so the loop forces completion with ``float(loss)`` — one
scalar readback whose ~0.1 s tunnel constant is amortized over the loop.

Also emits a v5e-16 step-time budget (analytic ICI exchange cost on top of
measured single-chip pieces; see ``docs/perf_tpu.md``) that makes the
north-star ">=2M samples/s on v5e-16" claim checkable.

Baseline: BASELINE.json north star — DLRM Criteo at >=2M samples/s on
v5e-16, i.e. 125k samples/s/chip. vs_baseline = value / 125000.

Fault tolerance (round 6; VERDICT r5 "What's missing" #1 — r5's record died
rc=124 with nothing to show): the backend is first probed in a watched
subprocess (``utils.runtime.probe_backend``) so a stalled tunnel yields a
parseable error record instead of a silent hang; every section's result is
appended (fsynced) to a JSONL sidecar (``DETPU_BENCH_SIDECAR``, default
``BENCH.partial.jsonl``) the moment it completes, so a process killed
mid-run keeps every finished section; and each section runs under a
best-effort ``SIGALRM`` deadline (``DETPU_BENCH_SECTION_DEADLINE_S``) so
one wedged variant cannot eat the whole run. The final line merges the
per-section statuses. ``DETPU_BENCH_SMOKE=1`` shrinks every shape to
CPU-testable toys (same code paths) for the fault-injection tests.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.models.dlrm import (
    DLRMConfig, DLRMDense, bce_with_logits)
from distributed_embeddings_tpu.ops.embedding_lookup import Ragged
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, HybridTrainState, SparseAdagrad, SparseSGD,
    init_hybrid_state, make_hybrid_train_loop, make_hybrid_train_step)
from distributed_embeddings_tpu.utils import obs, power_law_ids

CRITEO_KAGGLE_SIZES = [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
]
# Criteo-1TB (MLPerf DLRM) vocab sizes: the model behind BASELINE.md's
# 8xA100 numbers and the north-star target. Single-sourced from
# tools/_profcommon so the bench, the plan-time capacity auditor
# (tools/plan_audit.py), and the profile tools price the same vector.
from tools._profcommon import CRITEO_1TB_SIZES
CAP = 2_000_000
BATCH = 65536
# steps scanned per dispatch by each variant's loop driver (see run_dlrm)
DLRM_STEPS_PER_CALL = 16
ZOO_STEPS_PER_CALL = 4
C1TB_STEPS_PER_CALL = 4
# CPU-sized smoke mode: identical code paths on toy shapes, so the fault
# layer (sidecar, deadlines, kill-mid-run) is testable without a chip;
# heavyweight sections (tiny zoo, full convergence) are skipped outright
SMOKE = bool(os.environ.get("DETPU_BENCH_SMOKE"))
if SMOKE:
    CRITEO_KAGGLE_SIZES = [min(s, 2000) for s in CRITEO_KAGGLE_SIZES]
    CRITEO_1TB_SIZES = [min(s, 2000) for s in CRITEO_1TB_SIZES]
    CAP = 1000
    BATCH = 256
    DLRM_STEPS_PER_CALL = 2
    ZOO_STEPS_PER_CALL = 2
    C1TB_STEPS_PER_CALL = 2
# crash-surviving per-section record (see module docstring)
SIDECAR_PATH = os.environ.get("DETPU_BENCH_SIDECAR", "BENCH.partial.jsonl")
# step-metrics sidecar (observability layer): written only under DETPU_OBS=1
OBS_SIDECAR_PATH = os.environ.get("DETPU_OBS_SIDECAR", "BENCH.metrics.jsonl")
_METRICS_LOGGER = None  # bound by main() when DETPU_OBS=1
PROBE_TIMEOUT_S = float(os.environ.get("DETPU_PROBE_TIMEOUT_S", "120"))
SECTION_DEADLINE_S = float(
    os.environ.get("DETPU_BENCH_SECTION_DEADLINE_S", "1200"))
_RECORDER = None  # bound by main(); _guard records through it
BASELINE_SAMPLES_PER_SEC_PER_CHIP = 125_000.0
# TPU v5e (v5 lite): 197 TFLOP/s bf16 peak, 819 GB/s HBM, ~100 GB/s
# effective per-chip all-to-all bandwidth over ICI (2D torus, 4x 400 Gbps
# links; conservative effective figure).
V5E_BF16_PEAK_FLOPS = 197e12
V5E_HBM_GBPS = 819.0
V5E_ICI_EFF_GBPS = 100.0


# compiles observed during TIMED loops (post-warmup). A healthy steady
# state compiles everything during warmup; any compile inside the clocked
# window means something retraces per step — the throughput poison the
# obs recompile counter exists to catch. Summed across sections and gated
# by tools/compare_bench.py (steady_state_recompiles == 0).
_STEADY_RECOMPILES = 0


def _compiles_now():
    """Current backend-compile count (0 when the listener is not
    installed — bare runs without DETPU_OBS keep the old behavior)."""
    return obs.counters().get("recompiles", 0)


def timed_loop(step, state, args, iters=24, warmup=3):
    """Threaded-state timing with forced completion via value readback."""
    global _STEADY_RECOMPILES
    loss = None
    for _ in range(warmup):
        loss, state = step(state, *args)
    _force(loss)  # drain the pipeline before starting the clock
    compiles0 = _compiles_now()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, state = step(state, *args)
    _force(loss)  # forces execution of the whole chain (tunnel-safe)
    dt = (time.perf_counter() - t0) / iters
    _STEADY_RECOMPILES += _compiles_now() - compiles0
    del state
    return dt


def _force(x):
    """Readback of one element (loop drivers return a [K] loss vector)."""
    return float(jnp.asarray(x).reshape(-1)[-1])


def dense_flops_per_sample(cfg, num_tables):
    """Fwd matmul FLOPs/sample; training ~3x (fwd + dgrad + wgrad)."""
    dims = [cfg.num_numerical_features] + cfg.bottom_mlp_dims
    f = sum(2 * a * b for a, b in zip(dims, dims[1:]))
    nf = num_tables + 1
    f += 2 * nf * nf * cfg.embedding_dim  # dot interaction gram
    top_in = nf * (nf - 1) // 2 + cfg.embedding_dim
    dims = [top_in] + cfg.top_mlp_dims
    f += sum(2 * a * b for a, b in zip(dims, dims[1:]))
    return 3 * f


def embedding_hbm_bytes_per_sample(num_tables, dim, param_bytes=4,
                                   hotness=1.0):
    """Rough embedding-table HBM traffic per sample: fwd row gather + SGD
    update read-modify-write of the touched row."""
    row = dim * param_bytes
    return num_tables * hotness * row * 3


def make_cfg(table_sizes, compute_dtype):
    """The one benchmarked model config — also the probe for the FLOPs and
    HBM-traffic estimates, so the timed model and the roofline math can't
    drift apart."""
    return DLRMConfig(table_sizes=table_sizes, embedding_dim=128,
                      num_numerical_features=13,
                      bottom_mlp_dims=(512, 256, 128),
                      top_mlp_dims=(1024, 1024, 512, 256, 1),
                      compute_dtype=compute_dtype)


def build_state(de, dense, cfg, emb_opt, tx, table_sizes, param_dtype,
                batch=None):
    batch = BATCH if batch is None else batch
    rng = np.random.default_rng(0)
    num = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, size=(batch, 1)), jnp.float32)
    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32) for _ in table_sizes])
    flat = de.init(jax.random.key(1), dtype=param_dtype)
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense_params,
        dense_opt_state=tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))
    return state, num, labels


def run_dlrm(table_sizes, compute_dtype, param_dtype=jnp.float32,
             ragged_hotness=None, batch=None,
             steps_per_call=DLRM_STEPS_PER_CALL,
             metrics_variant=None):
    """One DLRM variant; returns samples/s. ``ragged_hotness`` switches the
    26 features to variable-hotness Ragged inputs with that mean hotness.

    ``metrics_variant`` names this variant in the step-metrics sidecar:
    under ``DETPU_OBS=1`` one *instrumented* step runs before the timed
    loop (its state output feeds the loop, so nothing is wasted) and its
    on-device metrics — exchange bytes, routed-id counts, overflow
    counters — are logged. The TIMED program itself is always built with
    ``with_metrics=False`` so the headline numbers measure the same
    program with or without ``DETPU_OBS``.

    Timing drives ``steps_per_call`` distinct pre-staged batches through ONE
    compiled program per dispatch (``make_hybrid_train_loop``'s ``lax.scan``)
    — per-step host dispatch measured ~25 ms through this environment's
    device tunnel (about a quarter of the r3 headline step), an artifact a
    production input pipeline amortizes exactly this way.
    ``steps_per_call=1`` restores the per-step-dispatch methodology of
    rounds 1-3."""
    batch = BATCH if batch is None else batch
    K = steps_per_call
    combiner = "sum" if ragged_hotness else None
    cfg = make_cfg(table_sizes, compute_dtype)
    de = DistributedEmbedding(cfg.embedding_configs(combiner=combiner),
                              world_size=1, compute_dtype=compute_dtype)
    dense = DLRMDense(cfg)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)

    rng = np.random.default_rng(0)
    if ragged_hotness is None:
        cat_stacks = [
            jnp.asarray(power_law_ids(rng, s, (K, batch)), jnp.int32)
            for s in table_sizes]
    else:
        # near-exact capacity: the reference's dynamic ragged carries no
        # padding, so minimal static headroom is the fair equivalent (every
        # padded position costs full gather/scatter price on TPU). One
        # UNIFORM capacity (max feature nnz, < 1% over the mean at this
        # batch) lets the plan executor batch all 26 features into a single
        # (width, capacity) group — one gather + one combine total.
        draws = []
        for s in table_sizes:
            hots = rng.integers(1, 2 * ragged_hotness + 1, size=(K, batch))
            splits = np.zeros((K, batch + 1), np.int32)
            np.cumsum(hots, axis=1, out=splits[:, 1:])
            draws.append((s, splits))
        cap = int(max(sp[:, -1].max() for _, sp in draws))
        cat_stacks = []
        for s, splits in draws:
            vals = np.zeros((K, cap), np.int32)
            for k in range(K):
                nnz = int(splits[k, -1])
                vals[k, :nnz] = power_law_ids(rng, s, (nnz,))
            cat_stacks.append(Ragged(values=jnp.asarray(vals),
                                     row_splits=jnp.asarray(splits)))

    state, num, labels = build_state(de, dense, cfg, emb_opt, tx,
                                     table_sizes, param_dtype, batch=batch)
    num_stack = jnp.broadcast_to(num, (K,) + num.shape)
    lab_stack = jnp.broadcast_to(labels, (K,) + labels.shape)

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    cats1 = jax.tree.map(lambda a: a[0], cat_stacks)
    if _METRICS_LOGGER is not None and metrics_variant is not None:
        # one instrumented step with a profile capture; the donated state
        # it returns seeds the timed loop below
        mstep = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                       lr_schedule=0.005, with_metrics=True,
                                       telemetry=False)
        with obs.profile_trace(f"bench_{metrics_variant}"):
            _, state, metrics = mstep(state, cats1, (num, labels))
        _METRICS_LOGGER.log_step(metrics, variant=metrics_variant,
                                 summary=obs.summarize(metrics))

    if K == 1:
        step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                         lr_schedule=0.005,
                                         with_metrics=False,
                                         nan_guard=False, telemetry=False)
        dt = timed_loop(step_fn, state, (cats1, (num, labels)))
        return batch / dt
    loop_fn = make_hybrid_train_loop(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.005, with_metrics=False,
                                     nan_guard=False, telemetry=False)
    dt = timed_loop(loop_fn, state,
                    (cat_stacks, (num_stack, lab_stack)), iters=4)
    return batch * K / dt


def run_tiny_zoo(opt_name, steps_per_call=ZOO_STEPS_PER_CALL,
                 param_dtype=jnp.float32):
    """Synthetic `tiny` zoo model (55 tables, 4.3 GB uncapped, batch 65536)
    — BASELINE.md's main table; the reference's 1xA100 Adagrad number is
    24.433 ms/iter (`synthetic_models/README.md:69`). Multi-step scanned
    dispatch like :func:`run_dlrm` (per-step tunnel dispatch is ~25 ms —
    12%+ of this step — and not a property of the program)."""
    from distributed_embeddings_tpu.models import (
        InputGenerator, build_synthetic, synthetic_models_v3)
    from distributed_embeddings_tpu.parallel import (
        SparseAdagrad, init_hybrid_state)

    mc = synthetic_models_v3["tiny"]
    de, dense, _ = build_synthetic(mc, 1)
    K = steps_per_call
    gen = InputGenerator(mc, BATCH, alpha=1.05, num_batches=K)
    if opt_name == "adagrad":
        emb_opt, tx = SparseAdagrad(), optax.adagrad(0.01)
    else:
        emb_opt, tx = SparseSGD(), optax.sgd(0.01)
    batches = [gen[k] for k in range(K)]
    num, cats, labels = batches[0]
    stack = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    num_stack, cat_stacks, lab_stack = stack
    out_widths = [int(de.strategy.global_configs[t]["output_dim"])
                  for t in de.strategy.input_table_map]
    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, w), jnp.float32) for w in out_widths])

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return jnp.mean((dense.apply(dp, n, emb_outs) - y) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1), dtype=param_dtype)
    loop_fn = make_hybrid_train_loop(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.01, with_metrics=False,
                                     nan_guard=False, telemetry=False)
    dt = timed_loop(loop_fn, state,
                    (cat_stacks, (num_stack, lab_stack)), iters=4)
    return dt / K * 1e3


def plan_exchange_bytes(table_sizes, dim, world, b_local, comm_bytes=2,
                        strategy="memory_balanced"):
    """Exact per-chip all-to-all bytes of one train step, derived from the
    executor's own exchange plan (VERDICT r3 Weak #5: the projection must
    price the plan's *padded* layout, not an idealized formula).

    The id exchange sends ``[world, l_max]`` int32 (this chip keeps its own
    row: ``(world-1) * l_max`` leaves the chip); the output exchange moves
    ``[world, b_local, s_max]`` activations forward and the same shape of
    cotangents back. ``l_max``/``s_max`` come from ``parallel/plan.py`` and
    include every dead-slot padding column the placement produces.
    """
    from distributed_embeddings_tpu.parallel import plan as plan_mod
    configs = [{"input_dim": int(s), "output_dim": dim}
               for s in table_sizes]
    de = DistributedEmbedding(configs, world_size=world, strategy=strategy)
    plan = plan_mod.build_plan(de.strategy, de.row_offsets_list,
                               [("d", 1)] * len(table_sizes), b_local)
    ids_bytes = (world - 1) * plan.l_max * 4
    out_bytes = 2 * (world - 1) * b_local * plan.s_max * comm_bytes
    live_cols = sum(plan.out_width(inst) for inst in plan.instances)
    pad_frac = 1.0 - live_cols / (world * plan.s_max)
    return ids_bytes + out_bytes, pad_frac, plan


def v5e16_budget(single_chip_samples_per_sec, table_sizes, dim, world=16):
    """v5e-16 step-time budget from the measured single-chip step plus the
    plan-derived (padding-inclusive) ICI exchange bytes.

    Model (see docs/perf_tpu.md "v5e-16 budget"): per-chip compute (dense
    MLP on the 1/world batch shard + embedding lookups/updates for the
    global batch over 1/world of the tables) scales ~1/world from the
    measured single-chip step; on top ride the two all-to-alls (bf16
    activations fwd + grads bwd) and the int32 id exchange over ICI, priced
    at the executor plan's exact padded layout.
    """
    b_local = BATCH // world
    t_compute = (1.0 / single_chip_samples_per_sec) * BATCH / world
    a2a_bytes, pad_frac, _ = plan_exchange_bytes(
        table_sizes, dim, world, b_local)
    t_ici = a2a_bytes / (V5E_ICI_EFF_GBPS * 1e9)
    t_step = t_compute + t_ici
    return {
        "v5e16_budget_ms": round(t_step * 1e3, 3),
        "v5e16_a2a_mb_per_chip": round(a2a_bytes / 1e6, 2),
        "v5e16_a2a_padding_frac": round(pad_frac, 4),
        "v5e16_projected_samples_per_sec": round(BATCH / t_step, 0),
    }


def run_criteo1tb_shard(world=16):
    """The north-star model itself (VERDICT r3 Missing #1): one chip runs
    exactly the embedding work a v5e-16 rank does for DLRM Criteo-1TB —
    the *heaviest* rank's tables under the world=16 memory_balanced
    placement, the full global batch of ids (65536), fwd gather + sparse
    backward + SGD scatter. The placement can't split tables (no column
    slicing here), so the heaviest rank holds the largest table whole:
    the 39,979,772-row one, ~10.2 GB bf16 of the model's 48 GB total —
    every other rank is lighter. The dense half and the ICI exchange are
    measured/priced separately by the ``criteo1tb_v5e16_*`` terms in
    :func:`main` (the dense MLP runs data-parallel at batch/world and is
    the same sub-millisecond cost the Kaggle bench measures).

    Returns ``(samples_per_sec, shard_tables, shard_rows)`` where
    samples_per_sec = global batch / measured embedding step time.
    """
    de16 = DistributedEmbedding(
        [{"input_dim": int(s), "output_dim": 128}
         for s in CRITEO_1TB_SIZES], world_size=world,
        strategy="memory_balanced")
    loads = [sum(int(c["input_dim"]) * int(c["output_dim"]) for c in cfgs)
             for cfgs in de16.strategy.local_configs_list]
    r = int(np.argmax(loads))
    shard_sizes = [int(c["input_dim"])
                   for c in de16.strategy.local_configs_list[r]]

    cfg = make_cfg(shard_sizes, jnp.bfloat16)
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=1,
                              compute_dtype=jnp.bfloat16)
    emb_opt = SparseSGD()
    K = C1TB_STEPS_PER_CALL
    rng = np.random.default_rng(0)
    cat_stacks = [jnp.asarray(power_law_ids(rng, s, (K, BATCH)), jnp.int32)
                  for s in shard_sizes]
    params = de.init(jax.random.key(0), dtype=jnp.bfloat16)

    def emb_body(params, cats_):
        local = de.local_view(params)
        outs, res = de.forward_with_residuals(local, cats_)
        # unit cotangents: gradient VALUES don't change the routing/scatter
        # work; the dense half that would produce them is timed separately
        ogs = [jnp.full_like(o, 1e-3) for o in outs]
        new_local, _ = de.sparse_apply_gradients(
            local, (), res, ogs, emb_opt, 0.005, scale=1.0)
        # restore the stacked [world, ...] layout so the scan carry type
        # matches its input
        return de.stacked_view(new_local), outs[0].astype(jnp.float32)[0, 0]

    def emb_loop(params, cat_stacks_):
        params, toks = jax.lax.scan(emb_body, params, cat_stacks_)
        return toks, params

    step = jax.jit(emb_loop, donate_argnums=(0,))
    dt = timed_loop(step, params, (cat_stacks,), iters=4)
    return BATCH * K / dt, len(shard_sizes), sum(shard_sizes)


def _guard(name, fn, default=None, retries=1, deadline_s=None):
    """One failed — or HUNG — variant must not kill the whole benchmark
    report. A transient tunnel/compile error gets one retry (VERDICT r3
    Weak #1 — r3 lost its tiny-zoo Adagrad capture to a dropped
    remote_compile connection that a retry would have recovered); each
    attempt runs under a best-effort SIGALRM deadline; and the outcome is
    appended to the fsynced JSONL sidecar the moment it is known, so a
    process killed mid-run keeps every section completed before the kill.
    ``DETPU_FAULT=die:bench.<name>`` kills the run at that section's start
    (the fault-injection tests' hook)."""
    from distributed_embeddings_tpu.utils import runtime

    return runtime.run_section(
        _RECORDER, f"bench.{name}", fn, default=default, retries=retries,
        deadline_s=SECTION_DEADLINE_S if deadline_s is None else deadline_s)


def run_dense_only(batch):
    """DLRMDense fwd/bwd/SGD step time (ms) at a per-chip batch — the dense
    term of the v5e-16 1TB budget (embedding activations enter as data)."""
    cfg = make_cfg([100] * 26, jnp.bfloat16)
    dense = DLRMDense(cfg)
    tx = optax.sgd(0.005)
    rng = np.random.default_rng(0)
    num = jnp.asarray(rng.normal(size=(batch, 13)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, size=(batch, 1)), jnp.float32)
    embs = [jnp.asarray(rng.normal(size=(batch, 128)), jnp.bfloat16)
            for _ in range(26)]
    params = dense.init(jax.random.key(0), num[:2], [e[:2] for e in embs])
    opt_state = tx.init(params)

    def step(state, embs_, batch_):
        params, opt_state = state
        n, y = batch_

        def loss_fn(p):
            return bce_with_logits(dense.apply(p, n, embs_), y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return loss, (optax.apply_updates(params, updates), opt_state)

    dt = timed_loop(jax.jit(step, donate_argnums=(0,)),
                    (params, opt_state), (embs, (num, labels)), iters=30)
    return dt * 1e3


RESIL_STEPS = 4 if SMOKE else 12


def run_resilient_overhead():
    """Self-healing-driver cost (ISSUE 3 acceptance: the guard must add no
    measurable step cost; the host driver's per-step readback is priced
    separately): the SAME single-chip DLRM variant driven four ways —

    * ``raw_step``: per-dispatch ``make_hybrid_train_step`` with the
      non-finite guard compiled OUT (``nan_guard=False``);
    * ``guard_step``: identical program with the guard compiled IN (the
      default build) — isolates the on-device guard cost;
    * ``resilient``: the guarded step under
      ``parallel.resilient.run_resilient`` (no checkpointing) — adds the
      driver's host loop incl. its per-step loss readback;
    * ``raw_loop``: the scanned ``make_hybrid_train_loop`` reference the
      headline uses (K steps per dispatch, guard off).

    Returns samples/s for each plus the two overhead fractions
    ``tools/compare_bench.py`` gates.
    """
    from distributed_embeddings_tpu.parallel import run_resilient

    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    batch = BATCH
    cfg = make_cfg(table_sizes, jnp.bfloat16)
    combiner = None
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)
    rng = np.random.default_rng(0)
    cats = [jnp.asarray(power_law_ids(rng, s, (batch,)), jnp.int32)
            for s in table_sizes]

    def build(loop=False, with_metrics=False, **step_kw):
        de = DistributedEmbedding(cfg.embedding_configs(combiner=combiner),
                                  world_size=1,
                                  compute_dtype=jnp.bfloat16)
        dense = DLRMDense(cfg)

        def loss_fn(dp, emb_outs, b):
            n, y = b
            return bce_with_logits(dense.apply(dp, n, emb_outs), y)

        state, num, labels = build_state(de, dense, cfg, emb_opt, tx,
                                         table_sizes, jnp.bfloat16,
                                         batch=batch)
        maker = make_hybrid_train_loop if loop else make_hybrid_train_step
        fn = maker(de, loss_fn, tx, emb_opt, lr_schedule=0.005,
                   with_metrics=with_metrics, **step_kw)
        return de, fn, state, num, labels

    iters = RESIL_STEPS
    de, raw, state, num, labels = build(nan_guard=False)
    dt_raw = timed_loop(raw, state, (cats, (num, labels)), iters=iters,
                        warmup=2)
    de, guard, state, num, labels = build(nan_guard=True)
    dt_guard = timed_loop(guard, state, (cats, (num, labels)), iters=iters,
                          warmup=2)

    def timed_metrics(nan_guard):
        # 3-tuple signature: timed_loop unpacks 2 — inline mini-loop
        de_, fn, st, num_, labels_ = build(with_metrics=True,
                                           nan_guard=nan_guard)
        global _STEADY_RECOMPILES
        loss = None
        for _ in range(2):
            loss, st, _m = fn(st, cats, (num_, labels_))
        _force(loss)
        compiles0 = _compiles_now()
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, st, _m = fn(st, cats, (num_, labels_))
        _force(loss)
        dt = (time.perf_counter() - t0) / iters
        # the instrumented/guarded variants are the likeliest to capture a
        # fresh host scalar per step — they ride the same steady-state
        # recompile gate as every timed_loop section
        _STEADY_RECOMPILES += _compiles_now() - compiles0
        return dt

    # the acceptance claim: with metrics already on (grad norms already
    # computed in-program) the guard's marginal cost is ~zero
    dt_m_raw = timed_metrics(nan_guard=False)
    dt_m_guard = timed_metrics(nan_guard=True)

    de, guard2, state, num, labels = build(nan_guard=True)
    # compile outside the timed window; the step donates its state, so
    # thread the returned one
    loss, state = guard2(state, cats, (num, labels))
    _force(loss)

    def data(start):
        for _ in range(start, iters):
            yield cats, (num, labels)

    res = run_resilient(guard2, state, data, de=de)
    sps_resilient = batch * res.steps_run / max(res.elapsed_s, 1e-9)

    K = DLRM_STEPS_PER_CALL
    de, loop, state, num, labels = build(loop=True, nan_guard=False)
    cat_stacks = [jnp.broadcast_to(c, (K,) + c.shape) for c in cats]
    num_stack = jnp.broadcast_to(num, (K,) + num.shape)
    lab_stack = jnp.broadcast_to(labels, (K,) + labels.shape)
    dt_loop = timed_loop(loop, state, (cat_stacks, (num_stack, lab_stack)),
                         iters=4)

    sps_raw, sps_guard = batch / dt_raw, batch / dt_guard
    sps_loop = batch * K / dt_loop
    return {
        "raw_step_samples_per_sec": round(sps_raw, 1),
        "nanguard_samples_per_sec": round(sps_guard, 1),
        "resilient_samples_per_sec": round(sps_resilient, 1),
        "raw_loop_samples_per_sec": round(sps_loop, 1),
        # the instrumented+guarded step now computes the per-table health
        # sentinels in-program (table_grad_norm / table_update_maxabs /
        # table_nonfinite): this throughput IS the sentinel-bearing step,
        # gated by compare_bench like any headline metric
        "sentinel_samples_per_sec": round(batch / dt_m_guard, 1),
        # on-device guard cost vs the unguarded step (metrics off: the
        # guard pays for the grad-energy reductions itself)
        "guard_overhead_frac": round(1.0 - sps_guard / sps_raw, 4),
        # guard cost when metrics are ALREADY on (the grad norms exist
        # in-program; acceptance: ~0)
        "guard_with_metrics_overhead_frac": round(
            1.0 - dt_m_raw / dt_m_guard, 4),
        # host-driver cost vs the same guarded per-dispatch step
        "driver_overhead_frac": round(1.0 - sps_resilient / sps_guard, 4),
        "steps": iters,
    }


def run_recovery():
    """Rollback-and-replay recovery cost (the chaos-path price tag, not a
    throughput headline): a small hybrid run with a checkpoint ring hits
    an engineered NaN batch, the driver rolls back to a ring entry,
    replays, quarantines the poison, and completes — reporting the
    restore wall-time (``rollback_wall_time_s``, the recovery's only
    off-the-training-path cost) and the drill's bookkeeping. The
    sentinel overhead itself rides ``sentinel_samples_per_sec`` in the
    ``resilient_overhead`` section (the instrumented+guarded step IS the
    sentinel-bearing program)."""
    import tempfile

    from distributed_embeddings_tpu.parallel import run_resilient

    table_sizes = [1000] * 8
    batch = 4096
    cfg = make_cfg(table_sizes, jnp.float32)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=1)
    dense = DLRMDense(cfg)

    def loss_fn(dp, emb_outs, b):
        n, y = b
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    state, num, labels = build_state(de, dense, cfg, emb_opt, tx,
                                     table_sizes, jnp.float32, batch=batch)
    step = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                  lr_schedule=0.005, with_metrics=True,
                                  nan_guard=True)
    rng = np.random.default_rng(0)
    cats = [jnp.asarray(power_law_ids(rng, s, (batch,)), jnp.int32)
            for s in table_sizes]
    nan_labels = jnp.asarray(np.asarray(labels).copy())
    nan_labels = nan_labels.at[(0,) * nan_labels.ndim].set(jnp.nan)
    steps = RESIL_STEPS
    bad = steps // 2

    def data(start):
        for i in range(start, steps):
            yield cats, (num, nan_labels if i == bad else labels)

    with tempfile.TemporaryDirectory(prefix="detpu_bench_rec_") as tmp:
        ck = os.path.join(tmp, "ck")
        t0 = time.perf_counter()
        res = run_resilient(step, state, data, de=de, checkpoint_dir=ck,
                            checkpoint_every_steps=2, resume=True,
                            emb_optimizer=emb_opt, dense_tx=tx,
                            escalate_after=1, keep_last_n=2,
                            metrics_interval=0)
        wall = time.perf_counter() - t0
    assert res.rollbacks == 1 and list(res.quarantined) == [bad], (
        res.rollbacks, res.quarantined)
    return {
        "steps": steps,
        "rollbacks": res.rollbacks,
        "quarantined_batches": len(res.quarantined),
        # the pure recovery cost: restoring the ring checkpoint (replayed
        # steps are ordinary training steps and are priced as such)
        "rollback_wall_time_s": res.rollback_time_s,
        "drill_wall_time_s": round(wall, 3),
    }


def run_reshard():
    """Offline checkpoint re-shard cost (elastic topology tooling): save a
    mid-size train state once, then rewrite it 1 -> 8 ranks (row-sliced)
    and back with ``utils.checkpoint.reshard_checkpoint`` — pure host
    file streaming, no device work — and price the rewrite in MB/s. The
    table data is copied byte-identically, so the round trip also
    re-asserts the bitwise A -> B -> A contract on real file sizes."""
    import tempfile

    from distributed_embeddings_tpu.parallel import init_hybrid_state
    from distributed_embeddings_tpu.parallel.strategy import (
        DistEmbeddingStrategy)
    from distributed_embeddings_tpu.utils import (
        save_train_state, verify_checkpoint)
    from distributed_embeddings_tpu.utils.checkpoint import (
        reshard_checkpoint)

    rows = 2_000 if SMOKE else 50_000
    configs = [{"input_dim": rows + 997 * i, "output_dim": 64}
               for i in range(8)]
    de = DistributedEmbedding(configs, world_size=1)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.1)
    state = init_hybrid_state(de, emb_opt,
                              {"w": jnp.ones((8 * 64, 1), jnp.float32)},
                              tx, jax.random.key(0))
    with tempfile.TemporaryDirectory(prefix="detpu_bench_reshard_") as tmp:
        src = os.path.join(tmp, "ck")
        save_train_state(src, de, state)
        mb = sum(
            os.path.getsize(os.path.join(dp_, f))
            for dp_, _, fs in os.walk(src) for f in fs) / 1e6
        target8 = DistEmbeddingStrategy(configs, 8, strategy="basic",
                                        row_slice_threshold=rows * 16)
        t0 = time.perf_counter()
        reshard_checkpoint(src, os.path.join(tmp, "ck8"), target8)
        reshard_checkpoint(os.path.join(tmp, "ck8"),
                           os.path.join(tmp, "ck1"), de)
        dt = time.perf_counter() - t0
        verify_checkpoint(os.path.join(tmp, "ck1"))  # CRCs intact
    return {"reshard_ckpt_mb": round(mb, 1),
            "reshard_rewrites": 2,
            "reshard_mb_per_s": round(2 * mb / max(dt, 1e-9), 1)}


def run_step_memory():
    """Static capacity accounting of the headline step (ISSUE 5): the
    capped bf16 DLRM step is abstractly lowered + compiled for THIS
    backend and XLA's own memory/cost analysis is read back —
    per-step peak-HBM estimate, argument/temp bytes, FLOPs — alongside
    the layout's param/optimizer-state budget. No execution, one extra
    compile; ``tools/compare_bench.py`` gates ``peak_hbm_mb`` like a
    throughput metric (>10% growth fails)."""
    from distributed_embeddings_tpu.analysis import memory as dmem

    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg = make_cfg(table_sizes, jnp.bfloat16)
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=1,
                              compute_dtype=jnp.bfloat16)
    dense = DLRMDense(cfg)

    def loss_fn(dp, emb_outs, b):
        n, y = b
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    rng = np.random.default_rng(0)
    num2 = jnp.asarray(rng.normal(size=(2, 13)), jnp.float32)
    dense_params = dense.init(
        jax.random.key(0), num2,
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
         for _ in table_sizes])
    cats = [jax.ShapeDtypeStruct((BATCH,), jnp.int32)
            for _ in table_sizes]
    batch_tree = (jax.ShapeDtypeStruct((BATCH, 13), jnp.float32),
                  jax.ShapeDtypeStruct((BATCH, 1), jnp.float32))
    rep = dmem.step_memory_report(
        de, loss_fn, optax.sgd(0.005), SparseSGD(), cats, batch_tree,
        dense_params=dense_params, param_dtype=jnp.bfloat16,
        nan_guard=False)
    comp = rep["compiled"]
    totals = rep["layout"]["totals"]

    def mb(x):
        return None if x is None else round(x / 1e6, 2)

    return {
        "peak_hbm_mb": mb(comp.get("peak_bytes_est")),
        "argument_mb": mb(comp.get("argument_bytes")),
        "temp_mb": mb(comp.get("temp_bytes")),
        "alias_mb": mb(comp.get("alias_bytes")),
        "flops": comp.get("flops"),
        "bytes_accessed_mb": mb(comp.get("bytes_accessed")),
        "param_mb_allocated": mb(totals["param_bytes_allocated"]),
        "param_mb_live": mb(totals["param_bytes_live"]),
        "opt_state_mb": mb(totals["opt_state_bytes"]),
        "layout_padding_frac": round(totals["padding_frac"], 4),
        "backend": comp.get("backend"),
        "error": comp.get("error"),
    }


def run_plan_audit():
    """Plan-time capacity model vs XLA's own accounting (ISSUE 8): the
    headline capped-bf16 DLRM layout is priced twice — by
    ``analysis/plan_audit.py``'s jax-free byte model and by the compiled
    step's ``memory_analysis()`` argument bytes — and the record carries
    the drift. ``tools/compare_bench.py`` fails a candidate whose drift
    exceeds 15% (the predictor must stay validated, not decorative) or
    whose plan violates its capacity contracts. The Criteo-1TB
    deployment plan (world=16, bf16, column-sliced — the north-star
    shape) is audited alongside, so its predicted per-rank HBM and
    a2a-payload figures are versioned with every bench round."""
    from distributed_embeddings_tpu.analysis import memory as dmem
    from distributed_embeddings_tpu.analysis import plan_audit as pa
    from distributed_embeddings_tpu.parallel import trainer as trainer_mod
    from tools._profcommon import (CRITEO1TB_BATCH, CRITEO1TB_COL_SLICE,
                                   CRITEO1TB_DIM, CRITEO1TB_WORLD)

    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg = make_cfg(table_sizes, jnp.bfloat16)
    de = DistributedEmbedding(cfg.embedding_configs(), world_size=1,
                              compute_dtype=jnp.bfloat16)
    dense = DLRMDense(cfg)

    def loss_fn(dp, emb_outs, b):
        n, y = b
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    rng = np.random.default_rng(0)
    num2 = jnp.asarray(rng.normal(size=(2, 13)), jnp.float32)
    dense_params = dense.init(
        jax.random.key(0), num2,
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
         for _ in table_sizes])
    cats = [jax.ShapeDtypeStruct((BATCH,), jnp.int32) for _ in table_sizes]
    batch_tree = (jax.ShapeDtypeStruct((BATCH, 13), jnp.float32),
                  jax.ShapeDtypeStruct((BATCH, 1), jnp.float32))
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)

    # --- the jax-free prediction, contract-checked
    rep = pa.audit_plan(de, BATCH, optimizer=emb_opt,
                        param_dtype=jnp.bfloat16, cat_inputs=cats,
                        label="bench_headline", contract=pa.default_contract())
    pred_emb = sum(r.alloc_param_bytes + r.opt_state_bytes
                   for r in rep.per_rank)

    # --- what XLA says the same step's arguments weigh (abstract
    # compile; nothing executes). Predicted arguments = the plan model's
    # embedding bytes + eval_shape's non-embedding state + the inputs —
    # so a drift isolates to the plan model's slab arithmetic.
    state = jax.eval_shape(
        lambda k, dp: trainer_mod.init_hybrid_state(
            de, emb_opt, dp, tx, k, dtype=jnp.bfloat16),
        jax.random.key(0), dense_params)
    leaf = dmem._leaf_bytes
    rest = leaf(state) - leaf(state.emb_params) - leaf(state.emb_opt_state)
    input_bytes = leaf(cats) + leaf(batch_tree)
    predicted_arg = pred_emb + rest + input_bytes
    step = trainer_mod.make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                              with_metrics=False,
                                              nan_guard=False)
    comp = dmem.compiled_step_report(step, (state, cats, batch_tree))
    measured = comp.get("argument_bytes")
    drift = (None if not measured
             else (predicted_arg - measured) / measured)

    # --- the north-star plan, audited at real shapes (pure arithmetic)
    from distributed_embeddings_tpu.parallel.strategy import (
        DistEmbeddingStrategy)
    c1tb = DistEmbeddingStrategy(
        [{"input_dim": int(s), "output_dim": CRITEO1TB_DIM,
          "combiner": None} for s in CRITEO_1TB_SIZES],
        CRITEO1TB_WORLD, strategy="comm_balanced",
        column_slice_threshold=None if SMOKE else CRITEO1TB_COL_SLICE)
    c1tb_rep = pa.audit_plan(
        c1tb, CRITEO1TB_BATCH, optimizer="sgd", param_dtype=jnp.bfloat16,
        dp_input=False, label="criteo1tb_v5e16",
        contract=None if SMOKE else pa.default_contract())

    def mb(x):
        return None if x is None else round(x / 1e6, 2)

    return {
        "predicted_argument_mb": mb(predicted_arg),
        "measured_argument_mb": mb(measured),
        "byte_drift_frac": None if drift is None else round(drift, 4),
        "emb_predicted_mb": mb(pred_emb),
        "groups": rep.n_groups,
        "s_max": rep.s_max,
        "violations": list(rep.violations),
        "compile_error": comp.get("error"),
        "criteo1tb": {
            "max_rank_gb": round(c1tb_rep.max_rank_bytes / 1024**3, 3),
            "total_a2a_mb_per_step": round(
                c1tb_rep.total_a2a_bytes_per_step / 1e6, 2),
            "imbalance_ratio": round(c1tb_rep.imbalance_ratio, 3),
            "groups": c1tb_rep.n_groups,
            "violations": list(c1tb_rep.violations),
        },
    }


def run_phase_budget():
    """Static per-phase HLO pass census of the headline step (ROADMAP
    3(a)): the capped bf16 DLRM step is abstractly compiled and its
    optimized HLO attributed to ``obs.scope`` phases — gather / scatter /
    sort / cumsum / all-to-all passes and estimated bytes per phase
    (``analysis/hlo_census.py``). No execution; one extra compile per
    optimizer family. ``tools/compare_bench.py`` fails a candidate whose
    per-phase gated pass count GROWS versus the baseline (the analogue of
    the recompiles==0 gate: a new row-op pass in the hot path is a
    regression even before it shows up as milliseconds), and fails any
    record whose census violates its own contracts (the headline SparseSGD
    build must keep its dedup phase empty).

    The Adagrad twin is censused alongside so the record documents the
    dedup budget both ways: ``sgd_dedup_row_ops`` must be 0, and
    ``adagrad_dedup_row_ops`` pins what the stateful family pays for the
    same shapes."""
    from distributed_embeddings_tpu.analysis import (
        census_train_step, default_contracts)

    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg = make_cfg(table_sizes, jnp.bfloat16)
    dense = DLRMDense(cfg)

    def loss_fn(dp, emb_outs, b):
        n, y = b
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    rng = np.random.default_rng(0)
    num2 = jnp.asarray(rng.normal(size=(2, 13)), jnp.float32)
    cats = [jax.ShapeDtypeStruct((BATCH,), jnp.int32) for _ in table_sizes]
    batch_tree = (jax.ShapeDtypeStruct((BATCH, 13), jnp.float32),
                  jax.ShapeDtypeStruct((BATCH, 1), jnp.float32))

    def one(opt, label):
        de = DistributedEmbedding(cfg.embedding_configs(), world_size=1,
                                  compute_dtype=jnp.bfloat16)
        dense_params = dense.init(
            jax.random.key(0), num2,
            [jnp.zeros((2, cfg.embedding_dim), jnp.float32)
             for _ in table_sizes])
        # with_metrics/nan_guard pinned like the timed headline sections:
        # the censused program must not vary with DETPU_OBS, or records
        # produced with and without it would diff different programs
        return census_train_step(
            de, loss_fn, optax.sgd(0.005), opt, cats, batch_tree,
            dense_params=dense_params, with_metrics=False, nan_guard=False,
            contracts=default_contracts(opt), label=label)

    sgd = one(SparseSGD(), "bench_headline_sgd")
    ada = one(SparseAdagrad(), "bench_adagrad_twin")

    def dedup_row_ops(rep):
        return sum(rep.passes("dedup", k)
                   for k in ("sort", "scatter", "cumsum", "gather"))

    return {
        # the headline (SparseSGD) program's per-phase budget — what the
        # compare_bench gate diffs round over round
        "phases": sgd.phase_table(),
        "sgd_dedup_row_ops": dedup_row_ops(sgd),
        "adagrad_dedup_row_ops": dedup_row_ops(ada),
        "adagrad_phases": ada.phase_table(),
        "violations": list(sgd.violations) + list(ada.violations),
        "total_instructions": sgd.total_instructions,
        "backend": sgd.backend,
    }


def _child_json(cmd_tail, timeout_s, label):
    """Run one static-gate tool in a CHILD process pinned to the
    virtual-device CPU backend (the audits and captures must never
    touch — or wait on — this process's accelerator tunnel) and return
    its ``--json`` payload. Shared by the ``schedule`` /
    ``phase_profile`` / ``pipeline`` sections so the env pinning,
    rc handling, and tempfile cleanup cannot drift apart."""
    import subprocess
    import tempfile

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    with tempfile.NamedTemporaryFile(
            mode="r", suffix=".json", delete=False) as tf:
        json_path = tf.name
    try:
        proc = subprocess.run(
            [sys.executable] + cmd_tail + ["--json", json_path],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            raise RuntimeError(
                f"{label} rc={proc.returncode}: {proc.stderr[-500:]}")
        with open(json_path, encoding="utf-8") as fh:
            return json.load(fh), proc
    finally:
        try:
            os.unlink(json_path)
        except OSError:
            pass


def run_schedule():
    """Schedule-graph baseline of the compiled step (the overlap
    ratchet's anchor): runs ``tools/schedule_audit.py`` in a CHILD
    process pinned to the virtual-device CPU backend (the static audit
    must never touch — or wait on — this process's accelerator tunnel)
    and embeds the dependency-DAG report: per-collective
    serialized/overlappable classification, the modeled critical path,
    and ``serialized_collective_fraction``. ``tools/compare_bench.py::
    check_schedule`` fails any candidate whose fraction or critical-path
    bytes GROW versus the baseline — overlap, once won, can never
    silently regress. Smoke mode audits the headline (dense) case only;
    full runs add the pipelined twin and the Criteo-1TB deployment
    shapes."""
    cfgs = ["dense"] if SMOKE else ["dense", "pipelined", "criteo1tb"]
    cases = {}
    violations = []
    for cfg in cfgs:
        reports, _ = _child_json(
            [os.path.join("tools", "schedule_audit.py"),
             "--config", cfg, "--no-drill"],
            600, f"schedule_audit --config {cfg}")
        for rep in reports:
            cases[rep["label"]] = {
                "serialized_collective_fraction":
                    rep["serialized_collective_fraction"],
                "critical_path_ns": rep["critical_path_ns"],
                "critical_path_bytes": rep["critical_path_bytes"],
                "collectives": [
                    {"phase": c["phase"],
                     "classification": c["classification"],
                     "on_critical_path": c["on_critical_path"]}
                    for c in rep["collectives"]
                    if c["op"] == "all-to-all"],
                "violations": list(rep["violations"]),
            }
            # the pipelined case fails through its OWN section
            # (schedule_pipelined) — folding its violations into the
            # headline would fail the serialized gate for a pipelined
            # defect and double-count the failure
            if not rep["label"].startswith("pipelined"):
                violations += rep["violations"]
    head = next(iter(cases.values()))
    out = {
        # headline (dense/world8) numbers — what check_schedule ratchets
        "serialized_collective_fraction":
            head["serialized_collective_fraction"],
        "critical_path_bytes": head["critical_path_bytes"],
        "critical_path_ns": head["critical_path_ns"],
        "cases": cases,
        "violations": violations,
    }
    pip_label = next((k for k in cases if k.startswith("pipelined")),
                     None)
    if pip_label is not None:
        # the pipelined twin lives ONLY in its own section
        # (schedule_pipelined, ratcheted by a second check_schedule
        # call): the K=2 step's modeled fraction (0.0 — every exchange
        # overlappable) and critical path can never silently regress
        # back toward the serialized baseline, and the headline section
        # stays a function of the serialized cases alone
        out["pipelined"] = dict(cases.pop(pip_label), label=pip_label)
    return out


def run_phase_profile(case=None):
    """Measured phase-time baseline (the observatory's anchor): runs
    ``tools/phase_profile.py`` in a CHILD process pinned to the
    virtual-device CPU backend (profiling must never disturb — or wait
    on — this process's accelerator tunnel) and embeds the measured
    report for the dense case (``case="pipelined"`` measures the K=2
    pipelined step instead — the ``phase_profile_pipelined`` section):
    per-phase p50 ms, the measured
    exchange/lookup/apply/dense breakdown, measured a2a and serialized
    fractions, the capture overhead (profiling is strictly opt-in — the
    timed headline sections never pay it), and the calibration drift
    flags against the schedule auditor's cost model.
    ``tools/compare_bench.py::check_phase_profile`` fails a candidate
    whose measured serialized fraction GROWS versus the baseline — so
    measured overlap, once the pipelined step (ROADMAP item 2) wins it,
    can never silently regress — or whose measured-vs-modeled
    classification disagrees."""
    cmd = [os.path.join("tools", "phase_profile.py")]
    cmd += (["--smoke"] if SMOKE and case is None
            else ["--case", case or "dense"])
    records, proc = _child_json(cmd, 900, "phase_profile")
    if not records:
        # rc can be 0 with zero cases when a capture failed non-strict;
        # an empty section must fail loudly, not ride the record hollow
        raise RuntimeError(
            f"phase_profile produced no case records: {proc.stderr[-500:]}")
    rec = records[0]
    prof = rec["profile"]
    return {
        "label": rec["label"],
        "measured_serialized_fraction":
            prof["measured_serialized_fraction"],
        "step_wall_ms_p50": prof["step_wall_ms_p50"],
        "group_ms": prof["group_ms"],
        "a2a_frac": prof["a2a_frac"],
        "concurrency": prof["concurrency"],
        "resolved_frac": prof["resolved_frac"],
        "collectives": prof["collectives"],
        "modeled_serialized_fraction":
            rec["modeled"]["serialized_collective_fraction"],
        "profile_overhead_frac": rec["profile_overhead_frac"],
        "plain_step_ms": rec["plain_step_ms"],
        "profiled_step_ms": rec["profiled_step_ms"],
        "calibration_scale":
            rec["calibration"]["scale_measured_over_modeled"],
        "calibration_flagged": rec["calibration"]["flagged"],
        "violations": rec["agreement_violations"],
        "steps": rec["steps"],
    }


def run_pipeline():
    """Pipelined-vs-serialized step A/B (ROADMAP item 2's bench rider):
    runs ``tools/pipeline_bench.py`` in a CHILD process pinned to the
    world-8 virtual-device CPU mesh — the only topology this environment
    exposes where the exchanges the pipeline hides actually exist (the
    world-1 headline sections have no all-to-all) — and embeds both
    ms/step figures, the speedup fraction, and the variant's own
    steady-state recompile count (folded into the record-wide gate).
    The throughput term is lifted top-level so ``tools/compare_bench.py``
    ratchets it like any headline metric; the modeled/measured overlap
    gates ride the ``schedule_pipelined`` / ``phase_profile_pipelined``
    sections next to this one."""
    global _STEADY_RECOMPILES
    rec, _ = _child_json([os.path.join("tools", "pipeline_bench.py")],
                         900, "pipeline_bench")
    _STEADY_RECOMPILES += int(rec.get("steady_state_recompiles") or 0)
    return rec


def run_serving():
    """Deadline-bounded serving at fixed QPS (ISSUE 15, the inference
    half of ROADMAP 4): runs ``tools/serve_bench.py`` in a CHILD
    process pinned to the world-8 virtual-device CPU mesh — requests
    coalesce into the padded-batch ladder around the donated-input
    no-grad forward — and embeds p50/p95/p99 latency over served
    requests, shed/deadline-missed counts, the padding fraction, and
    the ladder's steady-state recompile count (folded into the
    record-wide gate: a ladder that retraces per request mix poisons
    its own latencies). The int8-rows-with-per-row-scales serving-table
    pricing rides inside (``int8_serving``).
    ``tools/compare_bench.py::check_serving`` fails a candidate whose
    p95 grows beyond 10%, whose section recompiles, or whose section
    disappears versus the baseline."""
    global _STEADY_RECOMPILES
    cmd = [os.path.join("tools", "serve_bench.py")]
    if SMOKE:
        cmd.append("--smoke")
    rec, _ = _child_json(cmd, 900, "serve_bench")
    _STEADY_RECOMPILES += int(rec.get("steady_state_recompiles") or 0)
    return rec


def run_telemetry_overhead():
    """Access-telemetry cost (ISSUE 5): the SAME single-chip DLRM step
    timed with the jit-carried telemetry compiled OUT (the headline
    program — telemetry defaults off, so headline numbers stay
    round-comparable) and compiled IN (sketch scatter-adds + top-k merge
    per step). Both ride the steady-state recompile gate."""
    from distributed_embeddings_tpu.analysis import telemetry as tel

    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    batch = BATCH if SMOKE else 16384
    cfg = make_cfg(table_sizes, jnp.bfloat16)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)
    rng = np.random.default_rng(0)
    cats = [jnp.asarray(power_law_ids(rng, s, (batch,)), jnp.int32)
            for s in table_sizes]

    def build(telemetry):
        de = DistributedEmbedding(cfg.embedding_configs(), world_size=1,
                                  compute_dtype=jnp.bfloat16)
        dense = DLRMDense(cfg)

        def loss_fn(dp, emb_outs, b):
            n, y = b
            return bce_with_logits(dense.apply(dp, n, emb_outs), y)

        state, num, labels = build_state(de, dense, cfg, emb_opt, tx,
                                         table_sizes, jnp.bfloat16,
                                         batch=batch)
        fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                    lr_schedule=0.005, with_metrics=False,
                                    nan_guard=False, telemetry=telemetry)
        return de, fn, state, num, labels

    global _STEADY_RECOMPILES
    iters = RESIL_STEPS
    de, off, state, num, labels = build(False)
    dt_off = timed_loop(off, state, (cats, (num, labels)), iters=iters,
                        warmup=2)

    tcfg = tel.config_from_env()
    de, on, state, num, labels = build(tcfg)
    telem = tel.init_telemetry(de, tcfg)
    loss = None
    for _ in range(2):  # 4-ary signature: timed_loop unpacks 2 — inline
        loss, state, telem = on(state, cats, (num, labels), telem)
    _force(loss)
    compiles0 = _compiles_now()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, state, telem = on(state, cats, (num, labels), telem)
    _force(loss)
    dt_on = (time.perf_counter() - t0) / iters
    # a carried state that retraced per step would poison this section's
    # numbers — same gate as every timed loop
    _STEADY_RECOMPILES += _compiles_now() - compiles0

    return {
        "telemetry_off_samples_per_sec": round(batch / dt_off, 1),
        "telemetry_samples_per_sec": round(batch / dt_on, 1),
        # conventional overhead reading: extra time per step relative to
        # the telemetry-off step (2x step time -> 1.0, not 0.5)
        "telemetry_overhead_frac": round(dt_on / dt_off - 1.0, 4),
        "sketch": dict(tcfg._asdict()),
        "batch": batch,
        "steps": iters,
    }


def run_streaming():
    """Streaming-vocab section (ISSUE 11): the day-k/day-k+1 replay in
    miniature. A planted per-id CTR signal over a LARGE external id
    space with Zipf skew and day-over-day drift (day k+1 keeps most of
    day k's head but introduces never-seen ids) is trained two ways:

    * **static** — one table sized at the FULL external vocab (the
      fiction production systems pay HBM for);
    * **dynamic** — a capacity-bounded streaming table at a fraction of
      the rows (slots + shared buckets; ``parallel/streaming.py``),
      admissions gated by the count-min sketch, approximate-LFU
      evictions, slot map jit-carried.

    Reported: train-on-day-k / eval-on-day-k+1 AUC for both, the
    per-rank HBM bytes of both plans priced by
    ``analysis.plan_audit.audit_plan`` (slot-map + sketch state
    included), admission/evict/bucket counters, and both step
    throughputs — the dynamic loop rides the same steady-state-recompile
    gate as every timed section."""
    from distributed_embeddings_tpu.analysis import plan_audit
    from distributed_embeddings_tpu.parallel import streaming as smod
    from distributed_embeddings_tpu.parallel import (
        StreamingConfig, init_streaming, make_hybrid_eval_step)
    from distributed_embeddings_tpu.utils import binary_auc

    global _STEADY_RECOMPILES
    vocab = 4_000 if SMOKE else 400_000
    capacity = vocab // 8
    buckets = max(64, capacity // 16)
    dim = 16
    batch = 256 if SMOKE else 4096
    steps = 8 if SMOKE else 200
    drift = 0.15  # day-k+1: this fraction of ids is never-before-seen
    rng = np.random.default_rng(11)
    # planted per-id logit: AUC is learnable exactly insofar as a model
    # can give each (hot) id its own embedding
    logits = rng.normal(size=(2 * vocab,)).astype(np.float32) * 2.0

    def day_batch(day, i):
        r = np.random.default_rng(1000 * day + i)
        ids = power_law_ids(r, vocab, (batch,)).astype(np.int64)
        if day > 0:  # day-k+1 drift: a slice of brand-new ids
            fresh = r.random(batch) < drift
            ids = np.where(fresh, vocab + power_law_ids(r, vocab,
                                                        (batch,)), ids)
        y = (r.random(batch) < 1.0 / (1.0 + np.exp(-logits[ids]))
             ).astype(np.float32)
        return ids, y

    def build(streaming_cfg):
        if streaming_cfg is None:
            configs = [{"input_dim": 2 * vocab, "output_dim": dim}]
        else:
            configs = [{"input_dim": capacity + buckets,
                        "output_dim": dim,
                        "streaming": {"capacity": capacity,
                                      "buckets": buckets}}]
        # 2 tables minimum (world 1 still needs tables >= ranks); a tiny
        # side table keeps the comparison honest — both models carry it
        configs.append({"input_dim": 100, "output_dim": dim})
        de = DistributedEmbedding(configs, world_size=1)
        emb_opt = SparseAdagrad()
        tx = optax.sgd(0.01)

        def loss_fn(dp, emb_outs, b):
            logit = jnp.sum(emb_outs[0], axis=-1) * dp["s"] \
                + 0.0 * jnp.sum(emb_outs[1])
            return bce_with_logits(logit, b)

        state = init_hybrid_state(de, emb_opt, {"s": jnp.ones(())}, tx,
                                  jax.random.key(0))
        step = make_hybrid_train_step(
            de, loss_fn, tx, emb_opt, lr_schedule=0.5,
            with_metrics=False, nan_guard=False, dynamic=streaming_cfg)
        return de, emb_opt, tx, loss_fn, state, step

    def pred_fn(dp, emb_outs, b):
        return jnp.sum(emb_outs[0], axis=-1) * dp["s"]

    side = np.zeros((batch,), np.int32)
    out = {}
    for label, cfg in (("static", None),
                       ("dynamic", StreamingConfig(
                           admit_min_count=2, evict_margin=1,
                           depth=4, buckets=4096))):
        de, emb_opt, tx, loss_fn, state, step = build(cfg)
        sstate = init_streaming(de, cfg) if cfg else None
        t_train = 0.0
        compiles0 = None
        for i in range(steps):
            ids, y = day_batch(0, i)
            cats = [jnp.asarray(ids), jnp.asarray(side)]
            yb = jnp.asarray(y)
            if i == 1:  # step 0 is the compile; clock the steady state
                _force(state.step)
                compiles0 = _compiles_now()
                t0 = time.perf_counter()
            if cfg is None:
                _, state = step(state, cats, yb)
            else:
                _, state, sstate = step(state, cats, yb, sstate)
        _force(state.step)
        t_train = time.perf_counter() - t0
        _STEADY_RECOMPILES += _compiles_now() - compiles0
        ev = make_hybrid_eval_step(de, pred_fn, dynamic=cfg)
        scores, labels_next = [], []
        for i in range(4):
            ids, y = day_batch(1, 10_000 + i)
            cats = [jnp.asarray(ids), jnp.asarray(side)]
            p = (ev(state, cats, None) if cfg is None
                 else ev(state, cats, None, sstate))
            scores.append(np.asarray(p))
            labels_next.append(y)
        auc = binary_auc(np.concatenate(labels_next),
                         np.concatenate(scores))
        report = plan_audit.audit_plan(de, batch, optimizer=emb_opt,
                                       label=f"streaming_{label}",
                                       streaming_config=cfg)
        out[f"{label}_auc_day_k1"] = round(float(auc), 4)
        out[f"{label}_samples_per_sec"] = round(
            batch * (steps - 1) / t_train, 1)
        out[f"{label}_hbm_bytes_per_rank"] = report.max_rank_bytes
        if cfg is not None:
            occ = smod.occupancy(de, sstate)
            out["admitted"] = occ["admitted"]
            out["evicted"] = occ["evicted"]
            out["bucket_ids"] = occ["bucket_ids"]
            out["hit_ids"] = occ["hit_ids"]
            out["occupancy_frac"] = occ["tables"][0]["occupancy_frac"]
            out["streaming_state_bytes"] = (
                report.per_rank[0].streaming_state_bytes)
    out["hbm_frac_of_static"] = round(
        out["dynamic_hbm_bytes_per_rank"]
        / max(out["static_hbm_bytes_per_rank"], 1), 4)
    out["auc_delta_vs_static"] = round(
        out["dynamic_auc_day_k1"] - out["static_auc_day_k1"], 4)
    out.update(vocab=vocab, capacity=capacity, buckets=buckets,
               batch=batch, steps=steps, drift_frac=drift)
    return out


def run_online():
    """Online learning section (ISSUE 16): the resilient streaming-vocab
    trainer and the serving coalescer in ONE process against ONE set of
    tables, RCU snapshots published on a fixed cadence
    (``parallel/online.py``). The planted per-id CTR stream trains while
    a WALL-CLOCK open-loop driver (``RealtimeDriver`` on its own thread
    of control, ISSUE 18) serves Zipfian requests from the published
    snapshots at a FIXED staleness budget (publish cadence 2, freshness
    SLO 4 steps) — so ``freshness_p95_s`` here measures true concurrent
    staleness, not step-paced pumping.

    Reported: the JOINT rates over one wall clock (train samples/s and
    serve QPS — the price of serving and publishing inside the training
    process), serve latency p95/p99 with the freshness percentiles next
    to them, served/shed counts, and the held-out AUC of the online
    model against an offline replay of the IDENTICAL stream with no
    serving at all — the RCU copies must leave the trajectory untouched,
    so the delta is ~0 (the bitwise version of this gate is
    ``tools/check_online.py``'s checkpoint-CRC identity). The section's
    steady-state recompiles (any mix of training, publication and
    serving) fold into the record-wide gate;
    ``tools/compare_bench.py::check_online`` fails a candidate whose
    section recompiles, whose freshness p95 exceeds the SLO, whose AUC
    stops tracking the replay, or whose section disappears versus the
    baseline."""
    import tempfile

    from distributed_embeddings_tpu.parallel import (
        OnlineConfig, OnlineRuntime, Overloaded, ServeConfig, Served,
        ServingRuntime, StreamingConfig, init_streaming,
        make_hybrid_eval_step, run_resilient)
    from distributed_embeddings_tpu.parallel import serving as sv
    from distributed_embeddings_tpu.utils import binary_auc

    global _STEADY_RECOMPILES
    vocab = 2_000 if SMOKE else 100_000
    capacity = vocab // 8
    buckets = max(64, capacity // 16)
    dim = 16
    batch = 256 if SMOKE else 2048
    steps = 8 if SMOKE else 80
    publish_every = 2
    slo_steps = 4
    rps = 4                       # sizing unit for the serve config
    req_n = 16 if SMOKE else 64   # samples per request
    # wall-clock arrival rate: roughly the old step-paced volume (a few
    # requests per train step) so the joint-throughput baselines carry
    qps = 30.0 if SMOKE else 8.0
    rng0 = np.random.default_rng(17)
    logits = rng0.normal(size=(vocab,)).astype(np.float32) * 2.0

    def planted(seed):
        r = np.random.default_rng(seed)
        ids = power_law_ids(r, vocab, (batch,)).astype(np.int64)
        y = (r.random(batch) < 1.0 / (1.0 + np.exp(-logits[ids]))
             ).astype(np.float32)
        return ids, y

    def make_batch(i):
        ids, y = planted(5000 + i)
        return ([jnp.asarray(ids), jnp.asarray(np.zeros(batch, np.int32))],
                jnp.asarray(y))

    def data(start):
        for i in range(start, steps):
            yield make_batch(i)

    scfg = StreamingConfig(admit_min_count=2, evict_margin=1,
                           depth=4, buckets=4096)

    def build():
        configs = [
            {"input_dim": capacity + buckets, "output_dim": dim,
             "streaming": {"capacity": capacity, "buckets": buckets}},
            {"input_dim": 100, "output_dim": dim},
        ]
        de = DistributedEmbedding(configs, world_size=1)
        emb_opt = SparseAdagrad()
        tx = optax.sgd(0.01)

        def loss_fn(dp, emb_outs, b):
            logit = jnp.sum(emb_outs[0], axis=-1) * dp["s"] \
                + 0.0 * jnp.sum(emb_outs[1])
            return bce_with_logits(logit, b)

        state = init_hybrid_state(de, emb_opt, {"s": jnp.ones(())}, tx,
                                  jax.random.key(0))
        sstate = init_streaming(de, scfg)
        step = make_hybrid_train_step(
            de, loss_fn, tx, emb_opt, lr_schedule=0.5, with_metrics=True,
            nan_guard=True, dynamic=scfg)
        return de, emb_opt, tx, state, sstate, step

    def pred(dp, emb_outs, b):
        return jnp.sum(emb_outs[0], axis=-1) * dp["s"]

    def auc_of(de, state, sstate):
        ev = make_hybrid_eval_step(de, pred, dynamic=scfg)
        scores, labels = [], []
        for i in range(4):
            ids, y = planted(9000 + i)  # held-out seeds
            cats = [jnp.asarray(ids),
                    jnp.asarray(np.zeros(batch, np.int32))]
            scores.append(np.asarray(ev(state, cats, None, sstate)))
            labels.append(y)
        return float(binary_auc(np.concatenate(labels),
                                np.concatenate(scores)))

    # ---- the joint run: train + publish + serve, one process
    de, emb_opt, tx, state, sstate, step = build()
    rt = ServingRuntime(
        de, pred, state,
        # top rung holds 2 steps of arrivals: one step's burst of
        # submissions never crosses the pressure threshold (q >= top
        # rung), so the ladder stays at level 0 under the FIXED load
        config=ServeConfig(max_batch=2 * rps * req_n, max_wait_ms=0.0,
                           deadline_ms=60_000.0,
                           max_queue=16 * rps * req_n),
        streaming=(scfg, sstate))
    rng = np.random.default_rng(7)
    marks = {}

    def mark(cur, loss, metrics, state_now):
        marks[cur] = time.perf_counter()

    with tempfile.TemporaryDirectory(prefix="detpu_bench_online_") as tmp:
        online = OnlineRuntime(
            rt, config=OnlineConfig(publish_every_steps=publish_every,
                                    freshness_max_steps=slo_steps),
            checkpoint_dir=os.path.join(tmp, "ck"))
        res = online.run(
            step, state, data, de=de,
            warmup_template=([np.zeros(req_n, np.int32),
                              np.zeros(req_n, np.int32)], None),
            make_request=lambda i: sv.synthetic_request(
                rng, [vocab, 100], req_n),
            realtime_qps=qps, realtime_drain_s=60.0, on_step=mark,
            streaming_state=sstate, emb_optimizer=emb_opt, dense_tx=tx,
            checkpoint_every_steps=max(steps // 4, 2),
            metrics_interval=0)
        t_end = time.perf_counter()
    s = res.serve_stats
    _STEADY_RECOMPILES += int(s["steady_state_recompiles"] or 0)
    served = [r_ for r_ in res.serve_results if isinstance(r_, Served)]
    shed = [r_ for r_ in res.serve_results if isinstance(r_, Overloaded)]
    # the steady window opens AFTER the first pump (train-step compile,
    # first publication, ladder warmup all behind it) and closes after
    # the final publish + drain — the joint rates split ONE wall clock
    window = t_end - marks[1]
    train_sps = batch * (steps - 1) / window
    auc_online = auc_of(de, res.train.state, res.train.streaming)

    # ---- the offline replay: the IDENTICAL stream, no serving at all
    de2, emb_opt2, tx2, state2, sstate2, step2 = build()
    marks2 = {}

    def mark2(cur, loss, metrics, state_now):
        marks2[cur] = time.perf_counter()

    r2 = run_resilient(step2, state2, data, de=de2, on_step=mark2,
                       emb_optimizer=emb_opt2, dense_tx=tx2,
                       streaming_state=sstate2, metrics_interval=0)
    # the driver defers the final step's host callback past the
    # generator's exhaustion — clock the steps the marks actually cover
    last2 = max(marks2)
    offline_sps = batch * (last2 - 1) / (marks2[last2] - marks2[1])
    auc_offline = auc_of(de2, r2.state, r2.streaming)

    def r(x, nd=3):
        return None if x is None else round(x, nd)

    return {
        "train_samples_per_sec": round(train_sps, 1),
        "serve_qps": round(len(served) / window, 1),
        "serve_samples_per_sec": round(len(served) * req_n / window, 1),
        "offline_samples_per_sec": round(offline_sps, 1),
        "joint_train_frac_of_offline": round(train_sps / offline_sps, 4),
        "latency_p95_ms": r(s["latency_p95_ms"]),
        "latency_p99_ms": r(s["latency_p99_ms"]),
        "freshness_p95_steps": s["freshness_p95_steps"],
        "freshness_p95_s": r(s["freshness_p95_s"], 6),
        "freshness_slo_steps": slo_steps,
        "publish_every_steps": publish_every,
        "snapshot_version": s["snapshot_version"],
        "served": len(served),
        "shed": len(shed),
        "auc_online": round(auc_online, 4),
        "auc_offline_replay": round(auc_offline, 4),
        "auc_delta_vs_replay": round(auc_online - auc_offline, 4),
        "steady_state_recompiles": int(s["steady_state_recompiles"]),
        "level": s["level"],
        "vocab": vocab, "capacity": capacity, "batch": batch,
        "steps": steps, "serve_mode": "realtime_open_loop",
        "realtime_qps": qps, "request_n": req_n,
    }


def run_obs_plane():
    """Observability-plane cost section (ISSUE 17): what the metrics
    plane itself charges, measured on a REAL world-1 serving runtime
    whose sketches were populated by actually serving requests.

    * ``stats_wall_us`` — one sketch-backed ``ServingRuntime.stats()``
      call, the read path that replaced the O(window) raw-list
      ``np.percentile`` sorts; this is the before/after instrument for
      the migration and the ratchet against the plane growing a heavy
      read path again;
    * ``render_wall_us`` / ``scrape_ms`` — the Prometheus text render
      of the runtime's live registry, and the full HTTP round-trip
      against the stdlib scrape endpoint on an ephemeral port (what a
      real scraper pays mid-load);
    * ``dump_ms`` — one flight-recorder black-box dump with a FULL ring
      (canonical-JSON CRC + atomic rename): the cost paid at the worst
      possible moment (the crash path), so it must stay cheap;
    * ``sketch_observe_ns`` — the hot-path write each ``Served`` pays
      6x (total latency + 5 stage spans).

    Costs ratchet (lower is better) via
    ``tools/compare_bench.py::check_obs_plane``; the serving p95 itself
    stays inside the existing ``check_serving`` gate — this section
    prices the instrument, not the instrumented."""
    import statistics
    import tempfile
    import urllib.request

    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, ServeConfig, ServingRuntime, init_hybrid_state)
    from distributed_embeddings_tpu.parallel import serving as sv
    from distributed_embeddings_tpu.utils import mplane

    global _STEADY_RECOMPILES
    sizes = [2000, 500]
    configs = [{"input_dim": v, "output_dim": 8} for v in sizes]
    de = DistributedEmbedding(configs, world_size=1)
    tx = optax.sgd(0.05)
    state = init_hybrid_state(de, SparseSGD(),
                              {"w": jnp.ones((8 * len(sizes) + 2, 1),
                                             jnp.float32) * 0.01},
                              tx, jax.random.key(0))

    def pred_fn(dp, outs, batch):
        x = jnp.concatenate(list(outs) + [batch], axis=-1)
        return jax.nn.sigmoid(x @ dp["w"])[:, 0]

    rt = ServingRuntime(de, pred_fn, state,
                        config=ServeConfig(max_batch=16, max_wait_ms=0.0,
                                           deadline_ms=60_000.0,
                                           max_queue=4096))
    rng = np.random.default_rng(3)
    tmpl = sv.synthetic_request(rng, sizes, 2, numerical=2)
    rt.warmup((tmpl.cats, tmpl.batch))

    # populate the sketches with REAL served latencies (no pacing sleeps:
    # submit small groups and flush — the sketch contents, not the QPS,
    # are what this section prices)
    requests = 64 if SMOKE else 512
    served = 0
    for i in range(requests):
        rt.submit(sv.synthetic_request(rng, sizes,
                                       int(rng.integers(1, 5)),
                                       numerical=2))
        if i % 4 == 3:
            served += sum(isinstance(r, sv.Served) for r in rt.poll())
    served += sum(isinstance(r, sv.Served) for r in rt.flush())
    _STEADY_RECOMPILES += rt.stats()["steady_state_recompiles"]

    def timed_us(fn, iters):
        fn()  # warm any lazy state out of the timed region
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters * 1e6

    iters = 50 if SMOKE else 300
    stats_us = timed_us(rt.stats, iters)
    render_us = timed_us(rt.metrics.render, iters)
    body = rt.metrics.render()

    # the scrape a real collector pays: full HTTP round-trip against the
    # stdlib endpoint on an ephemeral port, registry rendered per GET
    exp = mplane.start_http_exporter(rt.metrics, port=0)
    try:
        def scrape():
            with urllib.request.urlopen(exp.url(), timeout=30) as resp:
                resp.read()
        scrape_ms = timed_us(scrape, 10 if SMOKE else 30) / 1e3
    finally:
        exp.stop()

    # flight-recorder dump with a FULL ring: the crash-path cost
    sketch_src = rng.normal(loc=5.0, scale=1.0, size=4096) ** 2
    with tempfile.TemporaryDirectory(prefix="detpu_bench_obs_") as tmp:
        path = os.path.join(tmp, "bb.blackbox.json")
        rec = mplane.FlightRecorder(path)
        for i in range(rec.capacity):
            rec.note_step(i, {f"m{k}": float(i * 31 + k)
                              for k in range(24)})
            rec.note_event("bench_tick", step=i)
        for _ in range(4):
            rec.note_stats(rt.stats())
        durs = []
        for _ in range(5 if SMOKE else 20):
            t0 = time.perf_counter()
            rec.dump("bench", reason="obs_plane_cost")
            durs.append((time.perf_counter() - t0) * 1e3)
        mplane.verify_blackbox(path)   # the timed dumps stayed CRC-intact
        dump_ms = statistics.median(durs)
        dump_bytes = os.path.getsize(path)

    sk = mplane.QuantileSketch()
    n = len(sketch_src)
    t0 = time.perf_counter()
    for v in sketch_src:
        sk.observe(v)
    observe_ns = (time.perf_counter() - t0) / n * 1e9

    return {
        "stats_wall_us": round(stats_us, 1),
        "render_wall_us": round(render_us, 1),
        "scrape_ms": round(scrape_ms, 3),
        "scrape_bytes": len(body.encode("utf-8")),
        "scrape_ok": 1,
        "dump_ms": round(dump_ms, 3),
        "dump_bytes": dump_bytes,
        "sketch_observe_ns": round(observe_ns, 1),
        "served": served,
        "requests": requests,
        "steady_state_recompiles": int(
            rt.stats()["steady_state_recompiles"]),
    }


def run_tracing():
    """Request-tracing cost section (ISSUE 20): what the trace plane
    charges the serve path, measured as two back-to-back world-1
    serving runs over the SAME request stream — tracing disabled
    (``ServingRuntime(trace=False)``: the ratcheted baseline) and
    tracing at retain-everything pressure (``sample=1.0``, every finish
    retained, the worst case a production sample rate can only improve
    on).

    * ``tracing_off_rps`` / ``tracing_on_rps`` — served-request
      throughput of each run; the off number rides the regression
      ratchet, the on number must stay within a bounded fraction of it;
    * ``overhead_us_per_req`` — the per-request wall delta the tracer
      charged under full retention;
    * ``ring_dump_bytes`` — the gzipped Chrome export of the full
      256-trace ring (the artifact a post-mortem ships);
    * ``span_sum_ok`` — 1 iff every retained trace's stage spans sum to
      its ``latency_ms`` within ``SPAN_SUM_TOL_MS``;
    * ``steady_state_recompiles`` — both runs; tracing must not perturb
      the serve ladder's compile cache."""
    import tempfile

    from distributed_embeddings_tpu.parallel import (
        DistributedEmbedding, ServeConfig, ServingRuntime,
        init_hybrid_state)
    from distributed_embeddings_tpu.parallel import serving as sv
    from distributed_embeddings_tpu.utils import reqtrace

    global _STEADY_RECOMPILES
    sizes = [2000, 500]
    configs = [{"input_dim": v, "output_dim": 8} for v in sizes]
    de = DistributedEmbedding(configs, world_size=1)
    tx = optax.sgd(0.05)
    state = init_hybrid_state(de, SparseSGD(),
                              {"w": jnp.ones((8 * len(sizes) + 2, 1),
                                             jnp.float32) * 0.01},
                              tx, jax.random.key(0))

    def pred_fn(dp, outs, batch):
        x = jnp.concatenate(list(outs) + [batch], axis=-1)
        return jax.nn.sigmoid(x @ dp["w"])[:, 0]

    requests = 64 if SMOKE else 512
    rng_tmpl = np.random.default_rng(3)
    tmpl = sv.synthetic_request(rng_tmpl, sizes, 2, numerical=2)

    def run_one(trace_on):
        global _STEADY_RECOMPILES
        rt = ServingRuntime(de, pred_fn, state,
                            config=ServeConfig(max_batch=16,
                                               max_wait_ms=0.0,
                                               deadline_ms=60_000.0,
                                               max_queue=4096),
                            trace=trace_on)
        if trace_on:
            # retain-everything pressure: the worst-case write path
            # (every finish hashes, copies, and rings), deterministic
            rt.traces = reqtrace.TraceBuffer(
                capacity=256, sample=1.0, seed=0, enabled=True,
                process="serve", top_fn=rt._trace_top_decile)
        rt.warmup((tmpl.cats, tmpl.batch))
        rng = np.random.default_rng(7)   # same stream both runs
        served = 0
        t0 = time.perf_counter()
        for i in range(requests):
            rt.submit(sv.synthetic_request(rng, sizes,
                                           int(rng.integers(1, 5)),
                                           numerical=2))
            if i % 4 == 3:
                served += sum(isinstance(r, sv.Served)
                              for r in rt.poll())
        served += sum(isinstance(r, sv.Served) for r in rt.flush())
        wall = time.perf_counter() - t0
        # read steady-state recompiles HERE, before the next run_one
        # compiles its own fresh ladder (the compile counter is
        # process-wide; a later read would misattribute those)
        steady = int(rt.stats()["steady_state_recompiles"])
        _STEADY_RECOMPILES += steady
        return rt, served, wall, steady

    rt_off, served_off, wall_off, steady_off = run_one(False)
    rt_on, served_on, wall_on, steady_on = run_one(True)

    snap = rt_on.traces.snapshot()
    span_sum_ok = int(bool(snap) and all(
        abs(sum(t["stages_ms"].values()) - t["latency_ms"])
        <= reqtrace.SPAN_SUM_TOL_MS for t in snap))
    with tempfile.TemporaryDirectory(prefix="detpu_bench_trace_") as tmp:
        path = os.path.join(tmp, "ring.trace.json.gz")
        rt_on.traces.export(path)
        ring_dump_bytes = os.path.getsize(path)

    return {
        "requests": requests,
        "tracing_off_rps": round(served_off / wall_off, 1),
        "tracing_on_rps": round(served_on / wall_on, 1),
        "overhead_us_per_req": round(
            (wall_on - wall_off) / requests * 1e6, 2),
        "retained": len(snap),
        "ring_capacity": rt_on.traces.stats()["capacity"],
        "span_sum_ok": span_sum_ok,
        "ring_dump_bytes": ring_dump_bytes,
        "trace_off_disabled": int(not rt_off.traces.stats()["enabled"]),
        "served_off": served_off, "served_on": served_on,
        "steady_state_recompiles": steady_off + steady_on,
    }


def run_isolated_serving():
    """Process-isolated serving section (ISSUE 18): what the process
    boundary costs and what the supervision buys, on the SAME model the
    ``tools/check_isolation.py`` drill uses.

    Three measurements over one wall-clock request factory:

    * **in-process baseline** — a warmed ``ServingRuntime`` driven by
      the open-loop driver; its served p50/p95/p99 are the floor;
    * **out-of-process** — a real spawned supervisor worker serving the
      same stream over the socket + shm boundary WHILE the trainer
      trains and publishes snapshots through shared memory (the joint
      train rate is the price of supervision inside the training
      process); the worker is killed mid-stream (``die@`` in the
      WORKER's env only) so crash containment, restart backoff, and
      restart-to-first-served are measured, not assumed;
    * **the supervision stats** — shm publish p95, restart count,
      typed-Unavailable outage answers, and request-rid conservation
      across the crash.

    ``tools/compare_bench.py::check_isolated_serving`` fails a record
    whose worker never restarted, whose futures leaked, whose reborn
    worker retraced, or whose boundary overhead blew past the
    in-process floor."""
    from distributed_embeddings_tpu.parallel import (
        RealtimeDriver, Served, ServingRuntime, SparseSGD,
        SuperviseConfig, Supervisor, Unavailable, run_resilient)
    from tools import isolation_common as ic

    global _STEADY_RECOMPILES
    qps = 60.0 if SMOKE else 80.0
    dur = 1.5 if SMOKE else 3.0
    steps = 12 if SMOKE else 30
    rows = 64                      # training batch rows
    die_at = max(10, int(qps * dur / 2))

    def pct(results):
        lats = np.array([r_.latency_ms for r_ in results
                         if isinstance(r_, Served)])
        if lats.size == 0:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None,
                    "served": 0}
        return {"p50_ms": round(float(np.percentile(lats, 50)), 3),
                "p95_ms": round(float(np.percentile(lats, 95)), 3),
                "p99_ms": round(float(np.percentile(lats, 99)), 3),
                "served": int(lats.size)}

    # ---- in-process floor: same model, same stream, no boundary
    built = ic.build(world=1)
    rt = ServingRuntime(built["de"], built["pred_fn"], built["state"],
                        config=built["config"],
                        streaming=built["streaming"])
    rt.warmup(built["template"])
    rt.install_snapshot(built["state"],
                        jax.tree.map(np.asarray, built["streaming"][1]),
                        version=1, train_step=0)
    drv = RealtimeDriver(rt, ic.make_request_fn(seed=21), qps,
                         duration_s=dur, burst_positions=(),
                         drain_s=30.0)
    drv.start()
    drv.join(timeout=120)
    inproc = pct(drv.results())
    _STEADY_RECOMPILES += rt.steady_recompiles()

    # ---- out-of-process: supervised worker + joint training + crash
    sup = Supervisor(
        "tools.isolation_common:worker_factory", {"world": 1},
        config=SuperviseConfig(
            env={"JAX_PLATFORMS": "cpu", "DETPU_FAULT": f"die@{die_at}",
                 "DETPU_METRICS_PORT": ""}))
    t0 = time.perf_counter()
    sup.start()
    start_s = time.perf_counter() - t0
    built2 = ic.build(world=1)
    sup.install_snapshot(built2["state"], built2["streaming"][1],
                         version=1, train_step=0)
    drv2 = RealtimeDriver(sup, ic.make_request_fn(seed=22), qps,
                          duration_s=None, burst_positions=(),
                          drain_s=60.0)
    drv2.start()

    def loss_fn(dp, outs, b):
        return sum(b[:, i % 2].mean() * jnp.mean(o)
                   for i, o in enumerate(outs)) * jnp.mean(dp["w"])

    step = make_hybrid_train_step(built2["de"], loss_fn, optax.sgd(0.05),
                                  SparseSGD(), with_metrics=True,
                                  nan_guard=True, dynamic=built2["scfg"])

    def make_batch(i):
        r_ = np.random.default_rng(4200 + i)
        cats = [jnp.asarray(r_.integers(0, sz, rows), jnp.int32)
                for sz in ic.SIZES]
        cats.append(jnp.asarray(
            r_.integers(i, i + 6, rows) * 7 + 10_000_000, jnp.int32))
        return cats, jnp.asarray(r_.normal(size=(rows, 2)), jnp.float32)

    def data(start):
        for i in range(start, steps):
            yield make_batch(i)

    marks, vc = {}, {"v": 1}

    def mark(cur, loss, metrics, state_now):
        marks[cur] = time.perf_counter()

    def pump(cur, loss, metrics, state_now, telem, stream):
        if cur % 2 == 0:
            vc["v"] += 1
            sup.install_snapshot(state_now, stream, version=vc["v"],
                                 train_step=cur)
        sup.note_train_step(cur)

    res = run_resilient(step, built2["state"], data, de=built2["de"],
                        on_step=mark, on_step_aux=pump,
                        emb_optimizer=SparseSGD(),
                        dense_tx=optax.sgd(0.05),
                        streaming_state=built2["streaming"][1],
                        metrics_interval=0)
    last = max(marks)
    train_sps = rows * (last - 1) / (marks[last] - marks[1])

    # the driver keeps the stream open until the crash has been
    # contained and the reborn worker serves again
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        blk = sup.stats(sync=False)["supervisor"]
        if blk["worker_alive"] and blk["restarts"] >= 1:
            break
        time.sleep(0.1)
    sup.install_snapshot(res.state, res.streaming, version=vc["v"] + 1,
                         train_step=res.step)
    time.sleep(0.5)                 # a post-restart tail gets served
    drv2.stop()
    drv2.join(timeout=120)
    results = drv2.results()
    st = sup.stats(sync=True)
    blk = st["supervisor"]
    sup.close()
    _STEADY_RECOMPILES += int(st.get("steady_state_recompiles") or 0)

    oop = pct(results)
    rids = sorted(r_.rid for r_ in results)
    unavailable = [r_ for r_ in results if isinstance(r_, Unavailable)]

    def r(x, nd=3):
        return None if x is None else round(x, nd)

    return {
        "inproc_p50_ms": inproc["p50_ms"],
        "inproc_p95_ms": inproc["p95_ms"],
        "inproc_p99_ms": inproc["p99_ms"],
        "inproc_served": inproc["served"],
        "oop_p50_ms": oop["p50_ms"],
        "oop_p95_ms": oop["p95_ms"],
        "oop_p99_ms": oop["p99_ms"],
        "oop_served": oop["served"],
        "joint_train_samples_per_sec": round(train_sps, 1),
        "shm_publish_p95_ms": r(blk.get("shm_publish_p95_ms")),
        "shm_region_bytes": blk.get("shm_region_bytes"),
        "worker_start_s": round(start_s, 2),
        "restart_to_first_served_ms": r(
            blk.get("restart_to_first_served_ms"), 1),
        "restarts": blk.get("restarts"),
        "crashes": blk.get("crashes"),
        "budget_ok": int(not blk.get("restart_budget_exhausted")),
        "unavailable": len(unavailable),
        "conserved": int(rids == list(range(len(rids)))),
        "freshness_p95_s": r(st.get("freshness_p95_s"), 6),
        "steady_state_recompiles": int(
            st.get("steady_state_recompiles") or 0),
        "qps": qps, "die_at": die_at, "train_steps": steps,
    }


CONV_STEPS = 6 if SMOKE else 360
CONV_BATCH = 512 if SMOKE else 8192


def run_convergence(param_dtype=jnp.float32, steps=CONV_STEPS,
                    batch=CONV_BATCH):
    """Train DLRM on the planted-signal task (models/learnable.py) through
    the full hybrid path on the real chip; returns (auc_start, auc_mid,
    auc_end). Chance is 0.5, the numerical-only ceiling ~0.64, the Bayes
    ceiling ~0.888 — ending well above 0.64 proves the sparse embedding
    path itself learns (the reference's analogous evidence is its Criteo
    AUC 0.80248, examples/dlrm/README.md:7)."""
    from distributed_embeddings_tpu.models.learnable import (
        LearnableClicks, train_dlrm_convergence)

    task = LearnableClicks([2000] * 8, num_numerical=4, seed=123, scale=1.2)
    return train_dlrm_convergence(task, world_size=1, steps=steps,
                                  batch=batch, embedding_dim=16,
                                  lr_schedule=0.01, param_dtype=param_dtype)


def run_convergence_sgd(steps=CONV_STEPS, batch=CONV_BATCH):
    """The SGD-only convergence capture (ROADMAP 1): the reference's
    flagship recipe — plain SGD on BOTH halves — on the planted task.
    Root-caused in docs/perf_tpu.md Round 9: the sparse path IS exact
    plain SGD (PR 8 equivalence test) and the per-table cotangents flow
    at the same magnitude as under Adam (the health sentinels measure
    them), but the pairwise-product signal at DLRM's 1/sqrt(vocab) init
    leaves every SGD-stable (lr, init-scale) combination pinned at the
    numerical-only solution within probe budgets — task conditioning,
    not a path defect. This capture records the recipe anyway so any
    future conditioning fix (feature normalization, warmup, interaction
    scaling) shows up as movement here; expect ~0.60 (the numerical-only
    region) until then, vs the 0.636 ceiling and Adam's ~0.87."""
    from distributed_embeddings_tpu.models.learnable import (
        LearnableClicks, train_dlrm_convergence)

    task = LearnableClicks([2000] * 8, num_numerical=4, seed=123, scale=1.2)
    return train_dlrm_convergence(task, world_size=1, steps=steps,
                                  batch=batch, embedding_dim=16,
                                  optimizer="sgd", lr_schedule=4.0,
                                  dense_lr=0.01)


def run_input_pipeline(world=16, batches=6):
    """End-to-end input pipeline at the v5e-16 projection shapes: raw-binary
    reader -> ``pack_mp_inputs`` (the DLRM example's default input path,
    ``examples/dlrm/main.py:prep_cats``) -> one chip's packed block on
    device. Returns sustained samples/s (VERDICT r4 #5: this rate must beat
    the projected step rate or the input side caps the projection; the
    reference's analogous path is its per-rank dataset slicing,
    ``examples/dlrm/main.py:166-190``)."""
    import shutil
    import tempfile

    rng = np.random.default_rng(0)
    n = BATCH * batches
    root = tempfile.mkdtemp(prefix="detpu_bench_ds_")
    try:
        return _input_pipeline_body(root, rng, n, world)
    finally:
        # _guard retries on failure: leaking a ~25 MB /tmp dataset per
        # failed attempt would accumulate across bench runs
        shutil.rmtree(root, ignore_errors=True)


def _input_pipeline_body(root, rng, n, world):
    import os

    from distributed_embeddings_tpu.utils import RawBinaryDataset
    from distributed_embeddings_tpu.utils.data import (
        get_categorical_feature_type)

    d = os.path.join(root, "train")
    os.makedirs(d, exist_ok=True)
    (rng.random(n) < 0.5).astype(np.bool_).tofile(
        os.path.join(d, "label.bin"))
    rng.normal(size=(n, 13)).astype(np.float16).tofile(
        os.path.join(d, "numerical.bin"))
    for i, s in enumerate(CRITEO_1TB_SIZES):
        power_law_ids(rng, s, (n,)).astype(
            get_categorical_feature_type(s)).tofile(
            os.path.join(d, f"cat_{i}.bin"))

    de = DistributedEmbedding(
        [{"input_dim": s, "output_dim": 128} for s in CRITEO_1TB_SIZES],
        world_size=world, dp_input=False, strategy="memory_balanced")
    ds = RawBinaryDataset(
        data_path=root, batch_size=BATCH, numerical_features=13,
        categorical_features=list(range(len(CRITEO_1TB_SIZES))),
        categorical_feature_sizes=CRITEO_1TB_SIZES, drop_last_batch=True)

    # HOST work only (reader + pack): the per-transfer constant of this
    # environment's device tunnel (~0.1 s) is not a property of a v5e
    # host, which feeds its local chips over PCIe; the per-chip block
    # volume is returned so the transfer rides the analytic budget like
    # the ICI term. numpy blocks only (mesh/device conversion skipped).
    def one_pass():
        tot = 0
        blk_bytes = 0
        for num, cats, labels in ds:
            mp = de.pack_mp_inputs(cats, as_numpy=True)
            blk_bytes = (mp.packed.nbytes // world
                         + num[:BATCH // world].nbytes)
            tot += num.shape[0]
        return tot, blk_bytes

    one_pass()  # warm the page cache
    t0 = time.perf_counter()
    tot, blk_bytes = one_pass()
    dt = time.perf_counter() - t0
    return tot / dt, blk_bytes


def main():
    global _RECORDER, _METRICS_LOGGER
    from distributed_embeddings_tpu.utils import runtime

    t_start = time.time()
    # fresh sidecar per run (the previous run's record belongs to the
    # driver's copy of it, not to this run)
    if os.path.exists(SIDECAR_PATH):
        os.remove(SIDECAR_PATH)
    _RECORDER = runtime.SectionRecorder(SIDECAR_PATH)
    if obs.metrics_enabled():
        # recompile counter must be listening BEFORE the first jit; the
        # metrics sidecar is fresh per run like the section sidecar
        if os.path.exists(OBS_SIDECAR_PATH):
            os.remove(OBS_SIDECAR_PATH)
        _METRICS_LOGGER = obs.MetricsLogger(OBS_SIDECAR_PATH)
        obs.install_compile_listener()
        obs.maybe_start_server()
    # time-boxed first backend touch, in a watched subprocess: a stalled
    # device tunnel must produce a parseable error record, not an rc=124
    probe = runtime.probe_backend(timeout_s=PROBE_TIMEOUT_S)
    _RECORDER.record("probe", ok=probe.ok, value=probe.to_json())
    if not probe.ok:
        print(json.dumps({
            "metric": "dlrm_samples_per_sec_per_chip", "value": 0.0,
            "unit": "samples/s", "vs_baseline": 0.0,
            "error": f"backend unavailable: {probe.error}",
            "backend": probe.platform,
            "device_count": probe.device_count,
            "probe": probe.to_json()}))
        return
    # environment stamp: lets compare_bench refuse to diff records from
    # different backends / device counts / jax versions (BENCH_r* rounds
    # were previously only comparable by convention)
    env_meta = dict(obs.env_stamp(), backend=probe.platform,
                    device_count=probe.device_count, smoke=SMOKE)
    _RECORDER.record("meta", ok=True, value=env_meta)

    capped = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg_probe = make_cfg(capped, jnp.bfloat16)

    fp32 = _guard("fp32", lambda: run_dlrm(capped, jnp.float32,
                                           metrics_variant="fp32"), 0.0)
    # rounds 1-3 comparable capture: bf16 compute over fp32 tables
    bf16 = _guard("bf16", lambda: run_dlrm(capped, jnp.bfloat16), 0.0)
    # headline candidate: bf16 tables too (the reference's headline is AMP —
    # fp16 storage/compute — examples/dlrm/README.md:8; bf16 needs no loss
    # scaling on TPU). Median-of-3 (VERDICT r3 Weak #1: single runs drifted
    # 2.6% between rounds; the spread is now part of the record).
    bf16p_runs = [x for x in [
        _guard(f"bf16_params_{i}",
               lambda: run_dlrm(capped, jnp.bfloat16,
                                param_dtype=jnp.bfloat16))
        for i in range(3)] if x]
    bf16p = float(np.median(bf16p_runs)) if bf16p_runs else 0.0
    bf16p_spread = (round((max(bf16p_runs) - min(bf16p_runs)) / bf16p, 4)
                    if len(bf16p_runs) > 1 and bf16p else None)
    # rounds 1-3 comparability: one capture with per-step dispatch
    bf16_per_dispatch = _guard(
        "bf16_per_dispatch",
        lambda: run_dlrm(capped, jnp.bfloat16, steps_per_call=1))
    # full Criteo-Kaggle vocabs, bf16 tables (~8.3 GB) — no cap
    uncapped_bf16 = _guard(
        "uncapped_bf16",
        lambda: run_dlrm(CRITEO_KAGGLE_SIZES, jnp.bfloat16,
                         param_dtype=jnp.bfloat16))
    # DCNv2-style multi-hot ragged lookups (hotness 1..30, mean ~15.5).
    # Batch 16384: this environment's chipless remote compiler crashes on
    # the larger ragged program (a toolchain limit — the same program
    # compiles on the CPU backend); samples/s is batch-insensitive here.
    ragged = _guard("multihot_ragged", lambda: run_dlrm(
        capped, jnp.bfloat16, ragged_hotness=15,
        batch=BATCH if SMOKE else 16384,
        metrics_variant="multihot_ragged"))
    # the north-star model itself: heaviest v5e-16 rank shard of
    # Criteo-1TB, global batch of ids, bf16 (VERDICT r3 Missing #1)
    c1tb = _guard("criteo1tb_shard", lambda: run_criteo1tb_shard())
    dense_ms = _guard("dense_only", lambda: run_dense_only(BATCH // 16))
    # the tiny zoo's tables are sized in GBs regardless of batch — skipped
    # outright in smoke mode rather than scaled
    tiny_adagrad_ms = None if SMOKE else _guard(
        "tiny_adagrad", lambda: run_tiny_zoo("adagrad"))
    tiny_sgd_ms = None if SMOKE else _guard(
        "tiny_sgd", lambda: run_tiny_zoo("sgd"))
    # bf16 tables (the reference's own headline precision is reduced too:
    # TF32 / AMP): halves every slab-wide pass of the dense-apply regime
    tiny_adagrad_bf16_ms = None if SMOKE else _guard(
        "tiny_adagrad_bf16",
        lambda: run_tiny_zoo("adagrad", param_dtype=jnp.bfloat16))
    best = max(fp32, bf16, bf16p)

    flops = dense_flops_per_sample(cfg_probe, len(capped))
    ebytes = embedding_hbm_bytes_per_sample(
        len(capped), cfg_probe.embedding_dim,
        param_bytes=2 if best == bf16p else 4)
    def r(x, nd=1):
        return None if x is None else round(x, nd)

    out = {
        "metric": "dlrm_samples_per_sec_per_chip",
        "value": round(best, 1),
        "unit": "samples/s",
        # the probe VERDICT, top-level: every number below was produced
        # on THIS backend, and tools/compare_bench.py refuses to diff
        # records whose backends disagree (the BENCH_r04-vs-r05 CPU/TPU
        # confusion trap — a CPU-proxy record must never silently gate a
        # TPU capture)
        "backend": probe.platform,
        "device_count": probe.device_count,
        "vs_baseline": round(best / BASELINE_SAMPLES_PER_SEC_PER_CHIP, 3),
        "variant": ("bf16_params" if best == bf16p
                    else "bf16" if best == bf16 else "fp32"),
        "fp32_samples_per_sec": round(fp32, 1),
        "bf16_samples_per_sec": round(bf16, 1),
        "bf16_params_samples_per_sec": round(bf16p, 1),
        "bf16_params_median_of": len(bf16p_runs),
        "bf16_params_spread_frac": bf16p_spread,
        "bf16_per_dispatch_samples_per_sec": r(bf16_per_dispatch),
        "steps_per_call": {"dlrm": DLRM_STEPS_PER_CALL,
                           "tiny_zoo": ZOO_STEPS_PER_CALL,
                           "criteo1tb": C1TB_STEPS_PER_CALL},
        "uncapped_bf16_samples_per_sec": r(uncapped_bf16),
        "multihot_ragged_samples_per_sec": r(ragged),
        "multihot_mean_hotness": 15.5,
        "dense_mfu_bf16_est": round(
            flops * max(bf16, bf16p) / V5E_BF16_PEAK_FLOPS, 4),
        "embedding_hbm_gbps_est": round(ebytes * best / 1e9, 1),
        "embedding_hbm_util_est": round(ebytes * best / 1e9 / V5E_HBM_GBPS,
                                        4),
        "tiny_zoo_adagrad_ms_per_iter": r(tiny_adagrad_ms),
        "tiny_zoo_sgd_ms_per_iter": r(tiny_sgd_ms),
        "tiny_zoo_adagrad_bf16_ms_per_iter": r(tiny_adagrad_bf16_ms),
        "tiny_zoo_vs_a100_1gpu": (
            None if tiny_adagrad_ms is None
            else round(24.433 / tiny_adagrad_ms, 3)),
    }
    if c1tb is not None:
        c1tb_sps, shard_tables, shard_rows = c1tb
        out["criteo1tb_shard_samples_per_sec"] = round(c1tb_sps, 1)
        out["criteo1tb_shard_tables"] = shard_tables
        out["criteo1tb_shard_rows"] = shard_rows
        if dense_ms is not None:
            # v5e-16 step on the 1TB model: measured heaviest-rank embedding
            # step + measured dense step at batch/16 + plan-derived ICI term
            a2a_bytes, pad_frac, _ = plan_exchange_bytes(
                CRITEO_1TB_SIZES, 128, 16, BATCH // 16)
            t = (BATCH / c1tb_sps + dense_ms / 1e3
                 + a2a_bytes / (V5E_ICI_EFF_GBPS * 1e9))
            out["criteo1tb_dense_ms_at_b4096"] = round(dense_ms, 2)
            out["criteo1tb_v5e16_step_ms"] = round(t * 1e3, 3)
            out["criteo1tb_v5e16_a2a_mb_per_chip"] = round(a2a_bytes / 1e6, 2)
            out["criteo1tb_v5e16_a2a_padding_frac"] = round(pad_frac, 4)
            out["criteo1tb_v5e16_projected_samples_per_sec"] = round(
                BATCH / t, 0)
    if best > 0:
        out.update(v5e16_budget(best, capped, cfg_probe.embedding_dim))
    inp = _guard("input_pipeline", run_input_pipeline)
    if inp is not None:
        rate, blk_bytes = inp
        out["input_pipeline_samples_per_sec"] = round(rate, 1)
        # per-chip input block per step; at ~10 GB/s host->chip PCIe this
        # rides the step budget like the ICI term (see docs/perf_tpu.md)
        out["input_pipeline_mb_per_chip_per_step"] = round(
            blk_bytes / 1e6, 2)
        proj = out.get("criteo1tb_v5e16_projected_samples_per_sec")
        if proj:
            # >= 1.0 means the input side cannot cap the v5e-16 projection
            out["input_pipeline_vs_projection"] = round(rate / proj, 3)
    stepmem = _guard("step_memory", run_step_memory)
    if stepmem is not None:
        out["step_memory"] = stepmem
        if stepmem.get("peak_hbm_mb") is not None:
            # lifted so compare_bench gates per-step peak HBM growth
            # (>10% fails) like any other headline metric
            out["peak_hbm_mb"] = stepmem["peak_hbm_mb"]
    pau = _guard("plan_audit", run_plan_audit)
    if pau is not None:
        # the capacity model rides the record so tools/compare_bench.py
        # can fail a candidate whose predicted-vs-measured byte drift
        # exceeds 15% or whose plan violates its capacity contracts
        out["plan_audit"] = pau
    pb = _guard("phase_budget", run_phase_budget)
    if pb is not None:
        # the census rides the record so tools/compare_bench.py can fail a
        # candidate whose per-phase gated pass counts regress (and any
        # record whose own pass-budget contracts are violated)
        out["phase_budget"] = pb
    pprof = _guard("phase_profile", run_phase_profile)
    if pprof is not None:
        # the MEASURED phase baseline rides the record so
        # tools/compare_bench.py::check_phase_profile can fail a
        # candidate whose measured serialized fraction grows or whose
        # measured-vs-modeled classification disagrees (the measured
        # half of the overlap ratchet)
        out["phase_profile"] = pprof
    if pprof is not None and not SMOKE:
        # the measured twin of the pipelined step: trace-parsed per-phase
        # ms + measured serialized fraction of the K=2 program, ratcheted
        # as its own section by check_phase_profile (skipped when the
        # dense capture already failed — its child would fail the same
        # way, and the gate reads absence as "capture crashed")
        pprof_pip = _guard("phase_profile_pipelined",
                           lambda: run_phase_profile("pipelined"))
        if pprof_pip is not None:
            out["phase_profile_pipelined"] = pprof_pip
    sched = _guard("schedule", run_schedule)
    if sched is not None:
        # the dependency-DAG baseline rides the record so
        # tools/compare_bench.py can fail a candidate whose
        # serialized_collective_fraction or modeled critical-path bytes
        # grow (the overlap ratchet)
        out["schedule"] = {k: v for k, v in sched.items()
                           if k != "pipelined"}
        if "pipelined" in sched:
            out["schedule_pipelined"] = sched["pipelined"]
    pipe = None if SMOKE else _guard("pipeline", run_pipeline)
    if pipe is not None:
        # pipelined-vs-serialized wall clock on the world-8 CPU mesh;
        # the throughput term is lifted so the regression gate sees it
        out["pipeline"] = pipe
        out["pipeline_samples_per_sec"] = pipe["pipeline_samples_per_sec"]
    serving = _guard("serving", run_serving)
    if serving is not None:
        # fixed-QPS latency percentiles of the serving runtime (p95
        # ratcheted by compare_bench's check_serving, recompiles folded
        # into the record-wide steady-state gate)
        out["serving"] = serving
    telov = _guard("telemetry_overhead", run_telemetry_overhead)
    if telov is not None:
        out["telemetry_overhead"] = telov
        out["telemetry_samples_per_sec"] = telov[
            "telemetry_samples_per_sec"]
    streaming = _guard("streaming", run_streaming)
    if streaming is not None:
        # capacity-bounded dynamic table vs the full-vocab static table
        # on the day-k/day-k+1 replay; the throughput term is lifted so
        # compare_bench's regression gate sees it like any other metric
        out["streaming"] = streaming
        out["streaming_samples_per_sec"] = streaming[
            "dynamic_samples_per_sec"]
    online = _guard("online", run_online)
    if online is not None:
        # concurrent train-and-serve at fixed staleness (publish cadence
        # + freshness SLO): joint train rate lifted top-level for the
        # generic throughput ratchet; the freshness/AUC/recompile gates
        # live in compare_bench's check_online
        out["online"] = online
        out["online_train_samples_per_sec"] = online[
            "train_samples_per_sec"]
    isolated = _guard("isolated_serving", run_isolated_serving)
    if isolated is not None:
        # the process boundary priced against the in-process floor, plus
        # crash-containment stats from a real mid-stream worker kill;
        # compare_bench's check_isolated_serving gates restart/budget/
        # conservation and the boundary-overhead multiple
        out["isolated_serving"] = isolated
    obsplane = _guard("obs_plane", run_obs_plane)
    if obsplane is not None:
        # what the observability plane itself charges (sketch-backed
        # stats(), Prometheus render + HTTP scrape, black-box dump);
        # compare_bench's check_obs_plane ratchets the costs and fails a
        # record whose scrape broke or whose section disappeared
        out["obs_plane"] = obsplane
    tracing = _guard("tracing", run_tracing)
    if tracing is not None:
        # gated by tools/compare_bench.py::check_tracing: tracing-off
        # throughput rides the regression ratchet, tracing-on must stay
        # within a bounded fraction of it, the span partition must hold
        out["tracing"] = tracing
    reshard = _guard("reshard", run_reshard)
    if reshard is not None:
        out["reshard"] = reshard
    resil = _guard("resilient_overhead", run_resilient_overhead)
    if resil is not None:
        # nested record for the bench report; the throughput terms are
        # ALSO lifted to the top level so compare_bench's regression gate
        # sees them like any other throughput metric
        out["resilient_overhead"] = resil
        out["nanguard_samples_per_sec"] = resil["nanguard_samples_per_sec"]
        out["resilient_samples_per_sec"] = resil[
            "resilient_samples_per_sec"]
        out["sentinel_samples_per_sec"] = resil[
            "sentinel_samples_per_sec"]
    recov = _guard("recovery", run_recovery)
    if recov is not None:
        out["recovery"] = recov
    conv = _guard("convergence", lambda: run_convergence(jnp.float32))
    # skip the bf16 variant when fp32 failed: its result would be dropped
    conv_bf16 = (_guard("convergence_bf16",
                        lambda: run_convergence(jnp.bfloat16))
                 if conv is not None else None)
    if conv is not None:
        out["convergence"] = {
            "task": "planted_pairwise_ctr",
            "auc_chance": 0.5, "auc_numerical_only": 0.636,
            "auc_bayes": 0.888,
            "auc_start": round(conv[0], 4), "auc_mid": round(conv[1], 4),
            "auc_end": round(conv[2], 4), "steps": CONV_STEPS,
            "batch": CONV_BATCH,
            "bf16_params_auc_end": (round(conv_bf16[2], 4)
                                    if conv_bf16 else None),
        }
    conv_sgd = _guard("convergence_sgd", run_convergence_sgd)
    if conv_sgd is not None:
        # the reference's flagship recipe (plain SGD both halves) on the
        # planted task — root-caused to a task-conditioning ceiling, not
        # a sparse-path defect (docs/perf_tpu.md Round 9); recorded so a
        # future conditioning fix shows up as movement
        out["convergence_sgd"] = {
            "recipe": "sgd_emb_lr4_dense_lr0.01",
            "auc_start": round(conv_sgd[0], 4),
            "auc_mid": round(conv_sgd[1], 4),
            "auc_end": round(conv_sgd[2], 4),
            "auc_numerical_only": 0.636,
        }
    # merge the sidecar's per-section statuses into the final record, so
    # the one JSON line also says which variants ran/failed/timed out
    sections = {}
    for rec in runtime.SectionRecorder.load(SIDECAR_PATH):
        sections[rec.get("section", "?")] = {
            k: rec.get(k) for k in ("ok", "elapsed_s", "error")
            if rec.get(k) is not None}
    out["sections"] = sections
    out["env"] = dict(env_meta, wall_time_s=round(time.time() - t_start, 1))
    if _METRICS_LOGGER is not None:
        # final counters record: recompiles (compile listener), runtime
        # retries, fault injections — the acceptance's recompile count
        _METRICS_LOGGER.log_counters(
            wall_time_s=round(time.time() - t_start, 1))
        out["obs_counters"] = obs.counters()
        # compiles that fired INSIDE a timed loop (warmup excluded):
        # nonzero means some section retraces at steady state, and
        # compare_bench fails the record on it
        out["steady_state_recompiles"] = _STEADY_RECOMPILES
    if SMOKE:
        out["smoke"] = True
    _RECORDER.record("final", ok=True, value=out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Headline benchmark: DLRM train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "dlrm_samples_per_sec_per_chip", "value": N, "unit": "samples/s",
   "vs_baseline": N, ...extras}

Config mirrors the reference's DLRM example (``examples/dlrm/``: MLPerf DLRM,
26 categorical features, embedding dim 128, bottom MLP 512-256-128, top MLP
1024-1024-512-256-1, SGD, global batch 65536) with Criteo-Kaggle-like vocab
sizes frequency-capped at 2M rows so the tables (~5.4 GB fp32) fit a single
chip's HBM — the single-chip slice of the Criteo-1TB target.

Two precision variants, like the reference's TF32 and AMP rows
(``examples/dlrm/README.md:7-8``):
  * fp32 end-to-end;
  * bf16 compute (fp32 master weights + embedding tables; bf16 MLP matmuls,
    bf16 embedding activations through the exchange — the TPU-native AMP).
The headline value is the faster variant (named in the "variant" extra;
normally bf16). Extras carry both raw numbers plus a
model-FLOPs-utilization estimate (dense matmul FLOPs / v5e bf16 peak) and an
achieved-HBM-bandwidth estimate for the embedding traffic, giving the roofline
context VERDICT r1 asked for.

Baseline: the north-star from BASELINE.json — DLRM Criteo-1TB at >=2M
samples/s on v5e-16, i.e. 125k samples/s/chip. vs_baseline = value / 125000.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_embeddings_tpu.models.dlrm import (
    DLRMConfig, DLRMDense, bce_with_logits)
from distributed_embeddings_tpu.parallel import (
    DistributedEmbedding, HybridTrainState, SparseSGD, make_hybrid_train_step)
from distributed_embeddings_tpu.utils import power_law_ids

CRITEO_KAGGLE_SIZES = [
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
]
CAP = 2_000_000
BATCH = 65536
BASELINE_SAMPLES_PER_SEC_PER_CHIP = 125_000.0
# TPU v5e (v5 lite): 197 TFLOP/s bf16 peak, 819 GB/s HBM.
V5E_BF16_PEAK_FLOPS = 197e12
V5E_HBM_GBPS = 819.0


def dense_flops_per_sample(cfg, num_tables):
    """Fwd matmul FLOPs/sample; training ~3x (fwd + dgrad + wgrad)."""
    dims = [cfg.num_numerical_features] + cfg.bottom_mlp_dims
    f = sum(2 * a * b for a, b in zip(dims, dims[1:]))
    nf = num_tables + 1
    f += 2 * nf * nf * cfg.embedding_dim  # dot interaction gram
    top_in = nf * (nf - 1) // 2 + cfg.embedding_dim
    dims = [top_in] + cfg.top_mlp_dims
    f += sum(2 * a * b for a, b in zip(dims, dims[1:]))
    return 3 * f


def embedding_hbm_bytes_per_sample(num_tables, dim, param_bytes=4):
    """Rough embedding-table HBM traffic per sample: fwd row gather + SGD
    update read-modify-write of the touched row."""
    row = dim * param_bytes
    return num_tables * row * 3  # 1x gather read + 1x update read + 1x write


def make_cfg(table_sizes, compute_dtype):
    """The one benchmarked model config — also the probe for the FLOPs and
    HBM-traffic estimates, so the timed model and the roofline math can't
    drift apart."""
    return DLRMConfig(table_sizes=table_sizes, embedding_dim=128,
                      num_numerical_features=13,
                      bottom_mlp_dims=(512, 256, 128),
                      top_mlp_dims=(1024, 1024, 512, 256, 1),
                      compute_dtype=compute_dtype)


def run_variant(table_sizes, compute_dtype):
    cfg = make_cfg(table_sizes, compute_dtype)

    de = DistributedEmbedding(cfg.embedding_configs(), world_size=1,
                              compute_dtype=compute_dtype)
    dense = DLRMDense(cfg)
    emb_opt = SparseSGD()
    tx = optax.sgd(0.005)

    rng = np.random.default_rng(0)
    num = jnp.asarray(rng.normal(size=(BATCH, 13)), jnp.float32)
    cats = [jnp.asarray(power_law_ids(rng, s, (BATCH,)), jnp.int32)
            for s in table_sizes]
    labels = jnp.asarray(rng.integers(0, 2, size=(BATCH, 1)), jnp.float32)

    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, cfg.embedding_dim), jnp.float32) for _ in table_sizes])

    flat = de.init(jax.random.key(1))
    state = HybridTrainState(
        emb_params=flat,
        emb_opt_state=emb_opt.init(flat),
        dense_params=dense_params,
        dense_opt_state=tx.init(dense_params),
        step=jnp.zeros((), jnp.int32))

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return bce_with_logits(dense.apply(dp, n, emb_outs), y)

    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.005)

    for _ in range(3):  # warmup / compile
        loss, state = step_fn(state, cats, (num, labels))
    jax.block_until_ready(loss)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, state = step_fn(state, cats, (num, labels))
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / iters
    del state
    return BATCH / dt


def run_tiny_zoo():
    """Synthetic `tiny` zoo model (55 tables, 4.3 GB uncapped, Adagrad,
    batch 65536) — BASELINE.md's main table; the reference's 1xA100 number
    is 24.433 ms/iter (`synthetic_models/README.md:69`)."""
    from distributed_embeddings_tpu.models import (
        InputGenerator, build_synthetic, synthetic_models_v3)
    from distributed_embeddings_tpu.parallel import (
        SparseAdagrad, init_hybrid_state)

    mc = synthetic_models_v3["tiny"]
    de, dense, _ = build_synthetic(mc, 1)
    gen = InputGenerator(mc, BATCH, alpha=1.05, num_batches=1)
    emb_opt = SparseAdagrad()
    tx = optax.adagrad(0.01)
    num, cats, labels = gen[0]
    out_widths = [int(de.strategy.global_configs[t]["output_dim"])
                  for t in de.strategy.input_table_map]
    dense_params = dense.init(
        jax.random.key(0), num[:2],
        [jnp.zeros((2, w), jnp.float32) for w in out_widths])

    def loss_fn(dp, emb_outs, batch):
        n, y = batch
        return jnp.mean((dense.apply(dp, n, emb_outs) - y) ** 2)

    state = init_hybrid_state(de, emb_opt, dense_params, tx,
                              jax.random.key(1))
    step_fn = make_hybrid_train_step(de, loss_fn, tx, emb_opt,
                                     lr_schedule=0.01)
    for _ in range(3):
        loss, state = step_fn(state, cats, (num, labels))
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(15):
        loss, state = step_fn(state, cats, (num, labels))
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / 15
    del state
    return dt * 1e3


def main():
    table_sizes = [min(s, CAP) for s in CRITEO_KAGGLE_SIZES]
    cfg_probe = make_cfg(table_sizes, jnp.bfloat16)

    fp32 = run_variant(table_sizes, jnp.float32)
    bf16 = run_variant(table_sizes, jnp.bfloat16)
    tiny_ms = run_tiny_zoo()
    best = max(fp32, bf16)

    flops = dense_flops_per_sample(cfg_probe, len(table_sizes))
    ebytes = embedding_hbm_bytes_per_sample(len(table_sizes),
                                            cfg_probe.embedding_dim)
    print(json.dumps({
        "metric": "dlrm_samples_per_sec_per_chip",
        "value": round(best, 1),
        "unit": "samples/s",
        "vs_baseline": round(best / BASELINE_SAMPLES_PER_SEC_PER_CHIP, 3),
        "variant": "bf16" if bf16 >= fp32 else "fp32",
        "fp32_samples_per_sec": round(fp32, 1),
        "bf16_samples_per_sec": round(bf16, 1),
        "dense_mfu_bf16_est": round(flops * bf16 / V5E_BF16_PEAK_FLOPS, 4),
        "embedding_hbm_gbps_est": round(ebytes * best / 1e9, 1),
        "embedding_hbm_util_est": round(ebytes * best / 1e9 / V5E_HBM_GBPS, 4),
        "tiny_zoo_adagrad_ms_per_iter": round(tiny_ms, 1),
        "tiny_zoo_vs_a100_1gpu": round(24.433 / tiny_ms, 3),
    }))


if __name__ == "__main__":
    main()

# Build orchestration (reference: Makefile building the CUDA .so; here the
# native piece is the C++ data-loader/id-generator shared library).

SHELL := /bin/bash

.PHONY: all native test test-fast bench clean pkg verify check-backend

all: native

native:
	$(MAKE) -C cc

test:
	python -m pytest tests/ -q

# quick tier for tight dev loops: skips @pytest.mark.slow (long compiles,
# RSS-bounded streaming, 2-process cluster); CI runs the full `test`
test-fast:
	python -m pytest tests/ -q -m "not slow"

bench:
	python bench.py

# the driver's tier-1 gate (ROADMAP.md "Tier-1 verify", verbatim semantics)
# plus the static no-eager-backend check — run before shipping a round
verify: check-backend
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

# fails if __graft_entry__.py / bench.py reintroduce a pre-probe backend
# touch (the r5 rc=124 root cause)
check-backend:
	python tools/check_no_eager_backend.py

pkg:
	python -m build --wheel 2>/dev/null || pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C cc clean
	rm -rf build dist *.egg-info

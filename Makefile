# Build orchestration (reference: Makefile building the CUDA .so; here the
# native piece is the C++ data-loader/id-generator shared library).

.PHONY: all native test test-fast bench clean pkg

all: native

native:
	$(MAKE) -C cc

test:
	python -m pytest tests/ -q

# quick tier for tight dev loops: skips @pytest.mark.slow (long compiles,
# RSS-bounded streaming, 2-process cluster); CI runs the full `test`
test-fast:
	python -m pytest tests/ -q -m "not slow"

bench:
	python bench.py

pkg:
	python -m build --wheel 2>/dev/null || pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C cc clean
	rm -rf build dist *.egg-info

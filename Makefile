# Build orchestration (reference: Makefile building the CUDA .so; here the
# native piece is the C++ data-loader/id-generator shared library).

SHELL := /bin/bash

.PHONY: all native test test-fast bench bench-diff bench-tpu clean pkg \
        verify lint plan-audit audit-step hlo-audit schedule-audit \
        concurrency-audit \
        check-backend check-obs check-obs-report check-resilience \
        check-reshard check-recovery check-streaming check-serving \
        check-online check-obsplane check-phase-profile check-isolation \
        check-tracing obs-report phase-profile

all: native

native:
	$(MAKE) -C cc

test:
	python -m pytest tests/ -q

# quick tier for tight dev loops: skips @pytest.mark.slow (long compiles,
# RSS-bounded streaming, 2-process cluster); CI runs the full `test`
test-fast:
	python -m pytest tests/ -q -m "not slow"

bench:
	python bench.py

# the driver's tier-1 gate (ROADMAP.md "Tier-1 verify", verbatim semantics)
# plus the static gates (detlint rules, the SPMD step auditor, the legacy
# no-eager-backend shim), the observability gate, and the
# preemption-recovery drill — run before shipping a round
verify: lint plan-audit audit-step hlo-audit schedule-audit \
        concurrency-audit check-backend \
        check-obs check-obs-report check-phase-profile check-resilience \
        check-reshard check-recovery check-streaming check-serving \
        check-online check-obsplane check-isolation check-tracing
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

# unified AST lint framework: eager-backend, env-registry, bare-except,
# host-fetch, named-scope-exchange, module-scope-jax (tools/detlint/)
lint:
	python -m tools.detlint

# plan-time capacity auditor: prices every reference plan (incl. the real
# Criteo-1TB vocab vector at world=16) backend-free — per-rank HBM, a2a
# payload/step, scatter-cliff slabs — enforces the PlanContracts, checks
# the byte model against analysis/memory.py, and self-drills two seeded
# violations (over-HBM, past-cliff); analysis/plan_audit.py
plan-audit:
	env JAX_PLATFORMS=cpu python tools/plan_audit.py --strict

# SPMD invariant auditor: traces the hybrid step abstractly on an
# 8-virtual-device CPU mesh and enforces the communication contract
# (2 fwd + 1 bwd all-to-all, no all_gather, no f64, donations intact)
audit-step:
	env JAX_PLATFORMS=cpu python tools/audit_step.py --strict

# optimized-HLO pass-budget auditor: compiles the hybrid step abstractly
# on the 8-virtual-device CPU mesh and enforces the per-phase pass budgets
# (dedup phase empty under SparseSGD, <=2 gathers per lookup group, no
# float convert round-trips; analysis/hlo_census.py)
hlo-audit:
	env JAX_PLATFORMS=cpu python tools/hlo_audit.py --strict

# schedule-graph auditor: compiles the hybrid step abstractly (incl. the
# streaming and Criteo-1TB cases), builds the dependency DAG of the
# optimized HLO, prices the critical path on the v5e cost model, and
# enforces the serialized-a2a baseline contracts + the StepSchedule
# overlap declaration check; self-drills a fake overlap-declaring
# schedule (analysis/schedule_audit.py)
schedule-audit:
	env JAX_PLATFORMS=cpu python tools/schedule_audit.py --strict

# concurrency auditor: jax-free AST lock-discipline analysis over the
# serving plane (shared attributes mutated from >=2 threads of control
# without a dominating lock, lock-acquisition-order cycles, blocking
# calls under a held lock, ConcurrencyContract drift) PLUS the
# explicit-state interleaving model checker proving the seqlock
# torn-read-detection and supervisor rid-monotonicity invariants over
# the full bounded interleaving space while refuting three seeded
# mutants (CRC check removed, stamps swapped, heartbeat deadline
# off-by-one); self-drills seeded Half-1 findings too
# (analysis/concurrency_audit.py)
concurrency-audit:
	env JAX_PLATFORMS=cpu python tools/concurrency_audit.py --strict

# measured phase-time observatory: run timed steps under
# jax.profiler.trace on the 8-virtual-device CPU mesh, attribute every
# trace event to its obs.scope phase, cross-check the measured
# serialized/overlapped classification against the schedule auditor's
# model, and render the calibration drift table (measured/modeled cost
# ratio per phase; analysis/phase_profile.py + tools/phase_profile.py)
phase-profile:
	env JAX_PLATFORMS=cpu python tools/phase_profile.py --strict

# the make verify smoke of the above: dense case only, 2 profiled steps
check-phase-profile:
	env JAX_PLATFORMS=cpu python tools/phase_profile.py --smoke --strict

# fails if __graft_entry__.py / bench.py reintroduce a pre-probe backend
# touch (the r5 rc=124 root cause); thin shim over the detlint rule
check-backend:
	python tools/check_no_eager_backend.py

# observability gate: obs.py imports cleanly under JAX_PLATFORMS=cpu and
# the DETPU_OBS=1 smoke bench emits a parseable step-metrics sidecar
check-obs:
	python tools/check_obs.py

# observatory render gate: synthetic metrics JSONL + telemetry summary
# through the full fusion/render path (no jax, sub-second)
check-obs-report:
	python tools/obs_report.py --selftest

# the embedding telemetry observatory (acceptance run): 8-virtual-device
# CPU mesh, Zipfian inputs with planted heavy hitters + engineered rank
# skew; fails unless the top-k recovers the plants, the skew shows in the
# per-rank ratios, and the telemetry is jit-carried (0 steady-state
# recompiles, no host callbacks in the audited jaxpr)
obs-report:
	env JAX_PLATFORMS=cpu python tools/obs_report.py

# preemption drill: SIGTERM a child resilient driver mid-run, resume it,
# and require the final state to match an uninterrupted run bit for bit
check-resilience:
	python tools/check_resilience.py

# elastic-topology drill: preempt an 8-virtual-device run, auto-resume it
# on 4 devices (in-place checkpoint re-shard, degradation logged), and
# require determinism + logical-state equality vs the uninterrupted run
check-reshard:
	python tools/check_reshard.py

# NaN-storm chaos drill: a child run with DETPU_FAULT=nan@<step> must roll
# back to a ring checkpoint, quarantine the poisoned batch (naming the
# unhealthy table via the per-table sentinels), finish clean, and match
# the stream-minus-poison run's final checkpoint bit for bit
check-recovery:
	python tools/check_recovery.py

# streaming-vocab drill: oovflood a child streaming run (novel ids land
# in the shared buckets, admissions fire), preempt + resume it, and
# require 0 steady-state recompiles plus a final checkpoint (slot-map
# aux included) CRC-identical to the uninterrupted run
check-streaming:
	python tools/check_streaming.py

# serving overload drill: a world-8 child serves a Zipfian request
# stream under DETPU_FAULT=slow:serve_step+burst@ (every flush slow, a
# 16x QPS spike at second 2); requires bounded p99, clean typed
# shedding with degrade/recover events, post-burst recovery, a
# bitwise-unchanged read-only streaming state, and 0 steady-state
# recompiles across the padded-batch ladder (parallel/serving.py)
check-serving:
	python tools/check_serving.py

# online learning drill: concurrent train-and-serve in one child under
# DETPU_FAULT=oovflood@+burst@ (never-seen training ids + an 8x serve
# spike); requires admissions, typed sheds only, post-burst recovery,
# monotone snapshot versions, freshness p95 within the SLO, bounded p99,
# 0 steady-state recompiles, and a training trajectory CRC-identical to
# the same stream without serving (parallel/online.py)
check-online:
	python tools/check_online.py

# process-isolation drill: a real spawned world-8 serving worker is
# SIGKILLed mid-burst (DETPU_FAULT=die@<rid> in the WORKER env only);
# the supervisor must contain the crash (typed Unavailable, zero lost
# futures), restart within the backoff budget, dump a CRC-intact
# blackbox, resume full service at 0 steady-state recompiles, and keep
# training CRC-identical to the serving-free run; tools/check_isolation.py
check-isolation:
	python tools/check_isolation.py

# cross-process tracing drill: world-8 supervised worker under
# die@<rid> + burst; one retained trace must CROSS the restart
# (worker_restarted / served_after_restart marks), every retained
# trace's stage spans must sum to latency_ms within 1e-6 ms (including
# the five-stage partitions pickled over the supervisor boundary), the
# federated /metrics scrape must serve the worker's families next to
# the supervisor's, at 0 steady-state recompiles; tools/check_tracing.py
check-tracing:
	python tools/check_tracing.py

# observability-plane drill: a world-8 child serves under burst chaos
# while its Prometheus endpoint is scraped MID-LOAD over real HTTP; the
# per-stage latency sketches must sum to the end-to-end served latency
# within 5% (the p99-attribution instrument) with 0 steady-state
# recompiles, and a second nan@-injected training child must leave a
# CRC-intact <dir>.blackbox.json post-mortem naming the unhealthy
# table(s) (utils/mplane.py)
check-obsplane:
	python tools/check_obsplane.py

# optional regression gate: diff two BENCH records, nonzero exit on a >10%
# throughput regression. Usage: make bench-diff OLD=BENCH_r04.json NEW=out.json
OLD ?= $(lastword $(sort $(wildcard BENCH_r*.json)))
NEW ?= BENCH.json
bench-diff:
	python tools/compare_bench.py $(OLD) $(NEW)

# one-command real-TPU capture (ROADMAP standing note ii): probe first,
# fail FAST with the tunnel verdict when the backend is CPU-only, and
# otherwise run the full bench (headline + pipelined + serving + online
# sections) stamping the backend platform into the record.
# Usage: make bench-tpu [OUT=BENCH_tpu.json]
OUT ?= BENCH_tpu.json
bench-tpu:
	python tools/bench_tpu.py --out $(OUT)

pkg:
	python -m build --wheel 2>/dev/null || pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C cc clean
	rm -rf build dist *.egg-info

# Build orchestration (reference: Makefile building the CUDA .so; here the
# native piece is the C++ data-loader/id-generator shared library).

.PHONY: all native test bench clean pkg

all: native

native:
	$(MAKE) -C cc

test:
	python -m pytest tests/ -q

bench:
	python bench.py

pkg:
	python -m build --wheel 2>/dev/null || pip wheel --no-deps -w dist .

clean:
	$(MAKE) -C cc clean
	rm -rf build dist *.egg-info

"""Package build (reference: ``setup.py`` + ``build_pip_pkg.sh``).

The TPU build has no CUDA compilation step; the optional native data-loader
extension under ``cc/`` builds with ``make -C cc`` (see Makefile) and is
loaded via ctypes with a pure-python fallback, so the wheel works without it.
"""

import os
import shutil
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))


def read_version():
    scope = {}
    with open(os.path.join(HERE, "distributed_embeddings_tpu", "version.py"),
              encoding="utf-8") as f:
        exec(f.read(), scope)  # noqa: S102 - own file
    return scope["__version__"]


class build_py_with_native(build_py):
    """Build and ship the native data-IO library inside the wheel.

    The reference wheel carries its compiled custom-op library
    (``build_pip_pkg.sh`` + ``setup.py:52-60``); here the native piece is
    ``cc/libdetpu_dataio.so``, staged into ``distributed_embeddings_tpu/
    utils/`` where ``utils/native.py`` looks for it. Best-effort: without a
    C++ toolchain the wheel still builds and every native entry point falls
    back to numpy."""

    def run(self):
        so = os.path.join(HERE, "cc", "libdetpu_dataio.so")
        try:
            subprocess.run(["make", "-C", os.path.join(HERE, "cc")],
                           check=True)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"[setup.py] native build skipped ({e}); "
                  "wheel will use the numpy fallbacks")
        if os.path.exists(so):
            shutil.copy2(so, os.path.join(
                HERE, "distributed_embeddings_tpu", "utils",
                "libdetpu_dataio.so"))
        super().run()


setup(
    name="distributed-embeddings-tpu",
    version=read_version(),
    description=("TPU-native large-embedding recommender training: "
                 "hybrid model/data-parallel embedding layers on JAX/XLA"),
    packages=find_packages(exclude=("tests", "examples")),
    package_data={"distributed_embeddings_tpu.utils": ["*.so"]},
    cmdclass={"build_py": build_py_with_native},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
    ],
)

"""Package build (reference: ``setup.py`` + ``build_pip_pkg.sh``).

The TPU build has no CUDA compilation step; the optional native data-loader
extension under ``cc/`` builds with ``make -C cc`` (see Makefile) and is
loaded via ctypes with a pure-python fallback, so the wheel works without it.
"""

import os

from setuptools import find_packages, setup


def read_version():
    here = os.path.dirname(os.path.abspath(__file__))
    scope = {}
    with open(os.path.join(here, "distributed_embeddings_tpu", "version.py"),
              encoding="utf-8") as f:
        exec(f.read(), scope)  # noqa: S102 - own file
    return scope["__version__"]


setup(
    name="distributed-embeddings-tpu",
    version=read_version(),
    description=("TPU-native large-embedding recommender training: "
                 "hybrid model/data-parallel embedding layers on JAX/XLA"),
    packages=find_packages(exclude=("tests", "examples")),
    package_data={"distributed_embeddings_tpu": ["cc/*.so"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
    ],
)
